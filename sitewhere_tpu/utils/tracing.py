"""Tracing / profiling hooks + the hierarchical span plane (ISSUE 10).

The reference defers tracing to the Istio mesh and measures stages with
Prometheus histograms (SURVEY.md §5.1). Here: lightweight host-side stage
spans feeding the metrics histograms, a wrapper around the JAX profiler
for device traces (viewable in TensorBoard/Perfetto), and the
``traceparent`` context that the flight recorder (utils/flight.py) and
the cluster RPC use to follow one batch across ranks (the Dapper-style
trace-context propagation the reference gets from Istio headers).

Trace ids are W3C-traceparent shaped (``00-<32 hex>-<16 hex>-01``) so a
future OTLP exporter can forward them unchanged. The CURRENT traceparent
lives in a :mod:`contextvars` variable — per-thread AND per-asyncio-task,
so the RPC server can bind it around a handler without cross-talk between
multiplexed calls.

Span plane (ISSUE 10) — three layers, one trace-id namespace:

* :class:`SpanTracer` — a fixed-size, lock-light ring of completed
  :class:`Span` records, one tracer per engine (exactly like the flight
  recorder). Spans carry trace id, span id, parent span id, rank, thread
  and tags. Sampling is HEAD-based and seeded-deterministic (a pure hash
  of the trace id decides at span end, so all of one trace's spans agree)
  with a TAIL-based always-keep for the slowest decile of each span name
  — a latency outlier survives even at aggressive sample rates.
* Timeline export — :func:`timeline_events` converts this rank's view of
  one trace (live tracer spans PLUS spans derived from flight-recorder
  lifecycle records, whose stage marks already timestamp
  decode→WAL→dispatch→device at zero extra hot-path cost) into
  Chrome-trace-event JSON that loads directly in Perfetto /
  chrome://tracing. ``pid`` is the rank, so the cluster facade can
  stitch per-rank event lists into ONE multi-rank timeline.
* :func:`profile_threads` — a wall-clock sampling profiler over the
  named engine threads (WAL commit thread, replica senders, forward
  retry pump, decode workers, ...), folded-stack output
  (flamegraph.pl-compatible); :func:`debug_bundle` snapshots config,
  recent flights, slowest traces, metrics exposition and
  WAL/archive/replication/QoS posture into one JSON document.

None of this touches ``engine.metrics()`` — the dispatch-shape equality
pin stays intact; span state lives on the tracer only.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import threading
import time
import zlib

from sitewhere_tpu.utils.metrics import REGISTRY

_STAGE_HIST = REGISTRY.histogram(
    "swtpu_stage_seconds", "host pipeline stage latency"
)

_local = threading.local()

# ------------------------------------------------------------ traceparent
_TRACEPARENT: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "swtpu_traceparent", default=None)
_SPAN_SEQ = itertools.count(1)


def new_trace_id(rank: int = 0) -> str:
    """A 32-hex trace id: rank + wall-clock ns + in-process sequence —
    unique across ranks and restarts without coordination (the forward-id
    recipe of parallel/cluster._next_fid, in W3C shape)."""
    return (f"{rank & 0xFFFF:04x}"
            f"{time.time_ns() & 0xFFFFFFFFFFFFFFFF:016x}"
            f"{next(_SPAN_SEQ) & 0xFFFFFFFFFFFF:012x}")


def new_traceparent(rank: int = 0, trace_id: str | None = None) -> str:
    """A W3C-style traceparent header value for a (possibly new) trace."""
    tid = trace_id or new_trace_id(rank)
    span = f"{(next(_SPAN_SEQ) ^ (rank << 48)) & 0xFFFFFFFFFFFFFFFF:016x}"
    return f"00-{tid}-{span}-01"


def trace_id_of(traceparent: str | None) -> str | None:
    """The 32-hex trace id inside a traceparent; None on malformed input
    (a peer shipping garbage must not poison the recorder index)."""
    if not traceparent:
        return None
    parts = traceparent.split("-")
    if len(parts) >= 2 and len(parts[1]) == 32:
        return parts[1]
    return None


def current_traceparent() -> str | None:
    """The traceparent bound to this thread/task, or None."""
    return _TRACEPARENT.get()


@contextlib.contextmanager
def bind_traceparent(traceparent: str | None):
    """Bind ``traceparent`` for the enclosed block (no-op on None, so an
    unpropagated call keeps whatever context it inherited)."""
    if traceparent is None:
        yield
        return
    token = _TRACEPARENT.set(traceparent)
    try:
        yield
    finally:
        _TRACEPARENT.reset(token)


@contextlib.contextmanager
def stage(name: str, **labels):
    """Span for one pipeline stage; nests (child spans record their own
    stage label), observations land in the shared histogram."""
    t0 = time.perf_counter()
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    stack.append(name)
    try:
        yield
    finally:
        stack.pop()
        _STAGE_HIST.observe(time.perf_counter() - t0, stage=name, **labels)


@contextlib.contextmanager
def device_trace(log_dir: str):
    """Capture a JAX device profile (xplane) for the enclosed block."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Decorator: trace a function as a stage span + XLA annotation."""
    import functools

    import jax

    def wrap(fn):
        @functools.wraps(fn)
        def inner(*args, **kwargs):
            with stage(name), jax.profiler.TraceAnnotation(name):
                return fn(*args, **kwargs)

        return inner

    return wrap


# ==========================================================================
# Span plane (ISSUE 10)
# ==========================================================================

# monotonic -> wall-clock anchor, taken ONCE at import: spans stamp cheap
# perf_counter_ns on the hot path and the exporter adds the anchor, so
# every span of a process shares one consistent clock (flight records
# anchor per record with time.time(); both land on the same wall axis)
_WALL_ANCHOR_NS = time.time_ns() - time.perf_counter_ns()


def _wall_us(perf_ns: int) -> float:
    return (perf_ns + _WALL_ANCHOR_NS) / 1000.0


class Span:
    """One completed (or in-flight) traced operation. ``t0_ns``/``t1_ns``
    are perf_counter_ns stamps; ``end()`` closes the span through its
    tracer (which applies the sampling verdict). Usable as a context
    manager: ``with tracer.begin("forward.hop", dst=3): ...``."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "rank",
                 "thread", "t0_ns", "t1_ns", "tags", "_tracer")

    def __init__(self, trace_id: str, span_id: str, parent_id: str | None,
                 name: str, rank: int, thread: str, t0_ns: int,
                 tags: dict | None, tracer: "SpanTracer | None"):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.rank = rank
        self.thread = thread
        self.t0_ns = t0_ns
        self.t1_ns = None
        self.tags = tags or {}
        self._tracer = tracer

    def annotate(self, **tags) -> None:
        self.tags.update(tags)

    def end(self, **tags) -> None:
        if tags:
            self.tags.update(tags)
        if self._tracer is not None:
            self._tracer.end(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.tags.setdefault("error", repr(exc))
        self.end()

    @property
    def dur_us(self) -> float:
        t1 = self.t1_ns if self.t1_ns is not None else time.perf_counter_ns()
        return max(0.0, (t1 - self.t0_ns) / 1000.0)

    def to_dict(self) -> dict:
        return {"traceId": self.trace_id, "spanId": self.span_id,
                "parentId": self.parent_id, "name": self.name,
                "rank": self.rank, "thread": self.thread,
                "startUs": round(_wall_us(self.t0_ns), 1),
                "durUs": round(self.dur_us, 1),
                "tags": dict(self.tags)}


class _NullSpan:
    """No-op span handed out while the tracer is disabled or sampling
    dropped the trace at begin() — hot paths stay branch-free."""

    trace_id = None
    span_id = None
    parent_id = None
    tags: dict = {}

    def annotate(self, **tags) -> None:
        pass

    def end(self, **tags) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> None:
        pass

    def to_dict(self) -> dict:
        return {}


NULL_SPAN = _NullSpan()


class SpanTracer:
    """Fixed-capacity ring of completed spans with a trace-id index —
    the span-level sibling of utils/flight.FlightRecorder.

    Head-based sampling is a seeded pure hash of the TRACE id (``sample``
    = keep fraction): deterministic, coordination-free, and consistent
    across every span (and every rank — same seed) of one trace. The
    tail-keep pass overrides a head-drop for spans in the slowest decile
    of their name's recent duration distribution, so the records an
    operator actually hunts (the p99 outliers) always survive. Both
    verdicts apply at ``end()``; begin/annotate are dict writes under the
    GIL, and the ring lock covers only slot insertion."""

    TAIL_WINDOW = 128          # recent durations kept per span name
    TAIL_REFRESH = 32          # recompute the decile threshold every N

    def __init__(self, capacity: int = 4096, rank: int = 0,
                 enabled: bool = True, sample: float = 1.0, seed: int = 0):
        if capacity < 1:
            raise ValueError("span tracer needs capacity >= 1")
        self.capacity = capacity
        self.rank = rank
        self.enabled = enabled
        self.sample = float(sample)
        self.seed = int(seed)
        self._ring: list[Span | None] = [None] * capacity
        self._head = 0
        self._by_id: dict[str, list[Span]] = {}
        self._lock = threading.Lock()
        self._local = threading.local()
        # per-name tail-keep state: (recent durations us, cached p90,
        # observations since refresh) — mutated under the GIL only; a
        # stale threshold costs one extra kept/dropped span, never a crash
        self._tail: dict[str, list] = {}
        self.recorded = 0          # spans inserted into the ring
        self.sampled_out = 0       # spans dropped by the head+tail verdict
        self.dropped = 0           # ring evictions

    # ---------------------------------------------------------- sampling
    def head_sampled(self, trace_id: str | None) -> bool:
        """Deterministic head-based verdict for one trace id."""
        if self.sample >= 1.0:
            return True
        if self.sample <= 0.0 or not trace_id:
            return False
        h = zlib.crc32(trace_id.encode()) ^ (self.seed * 0x9E3779B1
                                             & 0xFFFFFFFF)
        return ((h & 0xFFFFFFFF) / 2**32) < self.sample

    def _tail_keep(self, name: str, dur_us: float) -> bool:
        """True when ``dur_us`` lands in the slowest decile of this span
        name's recent distribution (always True until enough history)."""
        st = self._tail.get(name)
        if st is None:
            st = self._tail[name] = [[], None, 0]
        window, p90, since = st
        window.append(dur_us)
        if len(window) > self.TAIL_WINDOW:
            del window[:len(window) - self.TAIL_WINDOW]
        st[2] = since + 1
        if p90 is None or st[2] >= self.TAIL_REFRESH:
            srt = sorted(window)
            p90 = st[1] = srt[max(0, (len(srt) * 9) // 10 - 1)]
            st[2] = 0
        if len(window) < 16:
            return True            # not enough history to call a decile
        # STRICT: a uniform distribution (every duration == p90) must not
        # defeat head-sampling by tail-keeping everything
        return dur_us > p90

    # ------------------------------------------------------------ record
    def begin(self, name: str, traceparent: str | None = None,
              trace_id: str | None = None, parent_id: str | None = None,
              **tags) -> Span | _NullSpan:
        """Open a span. Trace id resolution: explicit ``trace_id``, then
        ``traceparent`` (explicit or the bound context's), then a fresh
        id. Parent defaults to this thread's innermost open span."""
        if not self.enabled:
            return NULL_SPAN
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        tid = trace_id or trace_id_of(traceparent or current_traceparent())
        if stack:
            # nested span: inherit the enclosing span's trace (and
            # parent) unless the caller pinned them explicitly
            if tid is None:
                tid = stack[-1].trace_id
            if parent_id is None:
                parent_id = stack[-1].span_id
        if tid is None:
            tid = new_trace_id(self.rank)
        span = Span(tid, f"{next(_SPAN_SEQ) & 0xFFFFFFFFFFFFFFFF:016x}",
                    parent_id, name, self.rank,
                    threading.current_thread().name,
                    time.perf_counter_ns(), tags, self)
        stack.append(span)
        return span

    def end(self, span: Span) -> None:
        span.t1_ns = time.perf_counter_ns()
        stack = getattr(self._local, "stack", None)
        if stack and stack[-1] is span:
            stack.pop()
        elif stack is not None:
            try:
                stack.remove(span)
            except ValueError:
                pass               # ended on a different thread — fine
        # short-circuit like record(): at sample=1.0 (the default) the
        # head verdict keeps everything and the tail-window bookkeeping
        # (append/trim/periodic sort) would be pure wasted hot-path work
        if self.head_sampled(span.trace_id) \
                or self._tail_keep(span.name, span.dur_us):
            self._insert(span)
        else:
            self.sampled_out += 1

    def record(self, name: str, t0_ns: int, t1_ns: int, *,
               trace_id: str | None, parent_id: str | None = None,
               thread: str | None = None, **tags) -> str | None:
        """Insert a retroactive span (explicit perf_counter_ns interval) —
        the seam for work measured on a thread that has no span context
        (shard decode workers, replica senders). Sampling applies exactly
        like end(). Returns the span id, or None when dropped/disabled."""
        if not self.enabled:
            return None
        tid = trace_id or new_trace_id(self.rank)
        span = Span(tid, f"{next(_SPAN_SEQ) & 0xFFFFFFFFFFFFFFFF:016x}",
                    parent_id, name, self.rank,
                    thread or threading.current_thread().name,
                    t0_ns, tags, None)
        span.t1_ns = t1_ns
        if self.head_sampled(tid) or self._tail_keep(name, span.dur_us):
            self._insert(span)
            return span.span_id
        self.sampled_out += 1
        return None

    def _insert(self, span: Span) -> None:
        with self._lock:
            old = self._ring[self._head]
            if old is not None:
                peers = self._by_id.get(old.trace_id)
                if peers is not None:
                    try:
                        peers.remove(old)
                    except ValueError:
                        pass
                    if not peers:
                        del self._by_id[old.trace_id]
                self.dropped += 1
            self._ring[self._head] = span
            self._head = (self._head + 1) % self.capacity
            self._by_id.setdefault(span.trace_id, []).append(span)
            self.recorded += 1

    # ------------------------------------------------------------- query
    def spans_of(self, trace_id: str) -> list[dict]:
        with self._lock:
            spans = list(self._by_id.get(trace_id, ()))
        return [s.to_dict() for s in spans]

    def recent(self, limit: int = 100, name: str | None = None) -> list[dict]:
        out = []
        with self._lock:
            i = (self._head - 1) % self.capacity
            for _ in range(self.capacity):
                s = self._ring[i]
                if s is not None and (name is None or s.name == name):
                    out.append(s)
                    if len(out) >= limit:
                        break
                i = (i - 1) % self.capacity
        return [s.to_dict() for s in out]

    def __len__(self) -> int:
        with self._lock:
            return sum(1 for s in self._ring if s is not None)


# --------------------------------------------------------------------------
# Chrome-trace-event timeline export
# --------------------------------------------------------------------------

# flight-record stage marks -> child-span intervals, per record kind.
# Each entry: (span name, start stage or None for record start, end
# stage[, require stage]). Stages a record never visited produce no
# span (same tolerance as utils/flight.stage_durations); an entry with
# a 4th element only applies to records that visited the require stage
# — the SPMD ingest path marks "route" between WAL append and the arena
# scatter (decode -> wal_append -> route -> arena_fill -> commit), so
# its spans key on it, while the single-chip entries self-suppress on
# SPMD records because their start refs resolve AFTER their ends.
# An SPMD record's root event also carries the dispatch skew tags
# ("shard_rows", "skew") the router stamps per dispatch — the Perfetto
# straggler-attribution breadcrumbs (ISSUE 18).
_FLIGHT_SPANS = {
    "ingest": (("decode", None, "decode"),
               ("arena_fill", "decode", "arena_fill"),
               ("wal_append", ("arena_fill", "decode"), "wal_append"),
               ("commit", ("wal_append", "arena_fill", "decode"), "commit"),
               ("spmd.wal", "decode", "wal_append", "route"),
               ("spmd.route", ("wal_append", "decode"), "route", "route"),
               ("spmd.scatter", "route", "arena_fill", "route"),
               ("spmd.commit", "arena_fill", "commit", "route"),
               ("wal_gate", "commit", "wal_durable"),
               ("dispatch_wait", ("wal_durable", "commit"), "dispatch"),
               ("device", "dispatch", "device_ready"),
               ("readback", "device_ready", "readback")),
    "query": (("lookup", None, "lookup"),
              ("device", "lookup", "device"),
              ("format", "device", "format"),
              ("archive_merge", "format", "archive")),
    "route": (("partition", None, "commit"),
              ("forward", "commit", "dispatch")),
}


def _flight_events(record: dict) -> list[dict]:
    """One flight record -> chrome trace events: a root X event spanning
    the whole lifecycle plus one child X event per visited stage
    interval. The record's ``stagesUs`` offsets are monotonic
    microseconds from ``startedMs`` (wall)."""
    stages = record.get("stagesUs") or {}
    base_us = record.get("startedMs", 0) * 1000.0
    kind = record.get("kind", "ingest")
    rank = record.get("rank", 0)
    tid = f"flight:{kind}"
    args = {k: v for k, v in record.items()
            if k not in ("stagesUs",) and not isinstance(v, (dict, list))}
    end = max(stages.values(), default=0.0)
    events = [{"name": kind, "cat": "flight", "ph": "X",
               "ts": base_us, "dur": end, "pid": rank, "tid": tid,
               "args": args}]

    def resolve(ref):
        if ref is None:
            return 0.0
        if isinstance(ref, tuple):
            for r in ref:
                v = stages.get(r)
                if v is not None:
                    return v
            return None
        return stages.get(ref)

    for entry in _FLIGHT_SPANS.get(kind, ()):
        name, start_ref, end_ref = entry[:3]
        if len(entry) > 3 and entry[3] not in stages:
            continue        # span only for records that visited the gate
        t1 = stages.get(end_ref)
        if t1 is None:
            continue
        t0 = resolve(start_ref)
        if t0 is None or t1 < t0:
            continue
        events.append({"name": f"{kind}.{name}", "cat": "flight",
                       "ph": "X", "ts": base_us + t0, "dur": t1 - t0,
                       "pid": rank, "tid": tid,
                       "args": {"traceId": record.get("traceId")}})
    return events


def _span_event(d: dict) -> dict:
    return {"name": d["name"], "cat": "span", "ph": "X",
            "ts": d["startUs"], "dur": d["durUs"], "pid": d["rank"],
            "tid": d.get("thread") or "span",
            "args": {"traceId": d["traceId"], "spanId": d["spanId"],
                     "parentId": d["parentId"], **d.get("tags", {})}}


def timeline_events(engine, trace_id: str) -> list[dict]:
    """This rank's Chrome-trace events for one trace id: flight-recorder
    lifecycle records (decode/WAL/dispatch/device intervals, derived at
    export time — the ingest hot path pays nothing new) merged with the
    live spans the tracer recorded (forward hops, replica send/apply,
    shard decode, query rounds, scheduler fires)."""
    events: list[dict] = []
    flight = getattr(engine, "flight", None)
    if flight is not None:
        for rec in flight.records_of(trace_id):
            events.extend(_flight_events(rec))
    tracer = getattr(engine, "tracer", None)
    if tracer is not None:
        events.extend(_span_event(d) for d in tracer.spans_of(trace_id))
    return events


def finish_timeline(trace_id: str, events: list[dict]) -> dict:
    """Wrap merged per-rank events into the document Perfetto loads
    directly: process metadata names each rank, threads sort stably, and
    events order by timestamp. String ``tid``/``pid`` values are mapped
    to stable small ints (chrome://tracing requires numerics) with
    ``thread_name``/``process_name`` metadata carrying the labels."""
    pids = sorted({e.get("pid", 0) for e in events}, key=str)
    pid_no = {p: i for i, p in enumerate(pids)}
    tid_no: dict[tuple, int] = {}
    out: list[dict] = []
    for p in pids:
        out.append({"name": "process_name", "ph": "M", "pid": pid_no[p],
                    "tid": 0, "args": {"name": f"rank {p}"}})
    for e in sorted(events, key=lambda e: e.get("ts", 0)):
        key = (e.get("pid", 0), str(e.get("tid", "span")))
        n = tid_no.get(key)
        if n is None:
            n = tid_no[key] = len([k for k in tid_no if k[0] == key[0]]) + 1
            out.append({"name": "thread_name", "ph": "M",
                        "pid": pid_no[key[0]], "tid": n,
                        "args": {"name": key[1]}})
        e = dict(e)
        e["pid"] = pid_no[key[0]]
        e["tid"] = n
        out.append(e)
    return {"traceId": trace_id, "displayTimeUnit": "ms",
            "traceEvents": out}


# --------------------------------------------------------------------------
# Wall-clock sampling thread profiler
# --------------------------------------------------------------------------

def _fold_frame(frame) -> list[str]:
    """One thread's stack, root-first, as ``module.function`` entries."""
    parts: list[str] = []
    while frame is not None:
        code = frame.f_code
        mod = frame.f_globals.get("__name__", "?")
        parts.append(f"{mod}.{code.co_name}")
        frame = frame.f_back
    parts.reverse()
    return parts


def profile_threads(seconds: float, interval_s: float = 0.01,
                    thread_filter=None) -> dict:
    """Sample every live thread's Python stack for ``seconds`` at
    ``interval_s`` cadence and fold the samples per thread name —
    ``{"thread;root;...;leaf": count}`` plus the flamegraph.pl-compatible
    text (``folded``). Pure wall-clock observation: no sys.settrace, no
    interpreter slowdown beyond the sampling thread's own GIL turns, so
    it is safe to point at a production engine. ``thread_filter`` (a
    predicate over thread names) narrows to specific engine threads; the
    sampling thread itself is always excluded."""
    import sys
    from collections import Counter

    me = threading.get_ident()
    counts: Counter = Counter()
    samples = 0
    deadline = time.perf_counter() + max(0.0, seconds)
    while True:
        names = {t.ident: t.name for t in threading.enumerate()}
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue
            name = names.get(ident, f"tid-{ident}")
            if thread_filter is not None and not thread_filter(name):
                continue
            counts[";".join([name] + _fold_frame(frame))] += 1
        samples += 1
        remaining = deadline - time.perf_counter()
        if remaining <= 0:
            break
        time.sleep(min(interval_s, remaining))
    folded = "\n".join(f"{stack} {n}" for stack, n
                       in sorted(counts.items()))
    return {"seconds": seconds, "intervalS": interval_s,
            "samples": samples, "threads": sorted(
                {s.split(";", 1)[0] for s in counts}),
            "stacks": dict(counts), "folded": folded}


# --------------------------------------------------------------------------
# Debug bundle
# --------------------------------------------------------------------------

def _slowest_traces(engine, top: int = 8) -> list[dict]:
    """The slowest completed ingest lifecycles currently in the flight
    ring, each with its rank-local timeline — the offline-triage payload
    scripts/trace2perfetto.py converts."""
    flight = getattr(engine, "flight", None)
    if flight is None:
        return []
    done = []
    for rec in flight.recent(limit=flight.capacity, kind="ingest"):
        end = (rec.get("stagesUs") or {}).get("device_ready")
        if end is not None and rec.get("traceId"):
            done.append((end, rec))
    done.sort(key=lambda t: -t[0])
    out = []
    for e2e_us, rec in done[:top]:
        tid = rec["traceId"]
        out.append({"traceId": tid, "e2eMs": round(e2e_us / 1000.0, 3),
                    "tenant": rec.get("tenant"),
                    "events": timeline_events(engine, tid)})
    return out


def debug_bundle(engine) -> dict:
    """One self-contained JSON document for offline triage: config,
    host/device counters, the strict-0.0.4 metrics exposition, recent
    flight records, the slowest traces (with rank-local timelines),
    recent spans, and WAL/archive/replication/forward/QoS posture.
    Everything here is a read-side snapshot — no engine lock is taken
    beyond what the individual surfaces already take."""
    import dataclasses

    from sitewhere_tpu.utils.metrics import (REGISTRY,
                                             export_engine_metrics)

    bundle: dict = {
        "generatedMs": int(time.time() * 1000),
        "rank": getattr(engine, "rank", 0),
    }
    cfg = getattr(engine, "config", None)
    if cfg is not None and dataclasses.is_dataclass(cfg):
        bundle["config"] = dataclasses.asdict(cfg)
    try:
        export_engine_metrics(engine)
        bundle["prometheus"] = REGISTRY.expose_text()   # strict 0.0.4,
        #                                                 no exemplars
    except Exception as e:                # a scrape failure must not
        bundle["prometheus"] = None       # take the bundle down with it
        bundle["prometheusError"] = repr(e)
    try:
        bundle["metrics"] = engine.metrics()
    except Exception as e:
        bundle["metrics"] = {"error": repr(e)}
    flight = getattr(engine, "flight", None)
    if flight is not None:
        bundle["flights"] = flight.recent(64)
        bundle["flightDropped"] = flight.dropped
    bundle["slowestTraces"] = _slowest_traces(engine)
    tracer = getattr(engine, "tracer", None)
    if tracer is not None:
        bundle["spans"] = tracer.recent(128)
        bundle["spanStats"] = {"recorded": tracer.recorded,
                               "sampledOut": tracer.sampled_out,
                               "dropped": tracer.dropped,
                               "capacity": tracer.capacity,
                               "sample": tracer.sample}
    wal = getattr(engine, "wal", None)
    if wal is not None:
        bundle["wal"] = {"groupCommit": wal.group_commit,
                         "fsyncs": getattr(wal, "fsyncs", None),
                         "commitGroups": getattr(wal, "commit_groups",
                                                 None)}
    arch = getattr(engine, "archive", None)
    if arch is not None:
        bundle["archive"] = {
            **arch.disk_usage(),
            "rows": arch.total_rows(),
            "lostRows": arch.lost_rows,
            "expiredRows": arch.expired_rows,
            "corruptSegments": arch.corrupt_segments,
            "queries": arch.queries,
            "plannerCalls": arch.planner_calls,
        }
    try:
        from sitewhere_tpu.parallel.replication import (
            cluster_health_payload)

        bundle["replication"] = cluster_health_payload(engine)
    except Exception:
        pass
    fq = getattr(engine, "forward_queue", None)
    if fq is not None:
        bundle["forward"] = fq.metrics()
    # elastic placement (ISSUE 15): the installed map epoch, per-range
    # handoff state, and the guard counters — the first stop when "why
    # did this write redirect" comes up mid-migration
    pm = getattr(engine, "placement", None)
    if pm is not None:
        try:
            bundle["placement"] = pm.payload()
        except Exception as e:
            bundle["placement"] = {"error": repr(e)}
    qos = getattr(engine, "qos", None)
    if qos is not None:
        bundle["qos"] = {"shedThreshold": qos.shed_threshold,
                         "bucketFill": qos.bucket_fill()}
    # conservation plane (ISSUE 14): the rank-local flow ledger +
    # verdict — one bundle answers "where are my events" without
    # another round trip. Never takes the bundle down with it.
    try:
        from sitewhere_tpu.utils.conservation import conservation_payload

        bundle["conservation"] = conservation_payload(engine)
    except Exception as e:
        bundle["conservation"] = {"error": repr(e)}
    # shard heat & skew plane (ISSUE 18): per-shard flow, the heat
    # maps, and the skew posture — a non-SPMD engine answers
    # {"spmd": False}. Never takes the bundle down with it.
    try:
        from sitewhere_tpu.utils.shardobs import spmd_heat_payload

        bundle["spmd"] = spmd_heat_payload(engine)
    except Exception as e:
        bundle["spmd"] = {"error": repr(e)}
    # device plane (ISSUE 11): the memory-ledger breakdown (a PEEK —
    # high-watermarks stay armed for the next scrape) plus per-family
    # compile posture, so one bundle answers "what is resident and what
    # has been retracing" without another round trip
    try:
        from sitewhere_tpu.utils.devicewatch import device_memory_payload

        bundle["device"] = device_memory_payload(engine)
    except Exception as e:          # never take the bundle down with it
        bundle["device"] = {"error": repr(e)}
    return bundle
