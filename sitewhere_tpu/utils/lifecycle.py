"""Minimal lifecycle runtime for host-side components.

The reference's microservice framework drives every component through an
initialize -> start -> stop -> terminate state machine with nested composition
(L1 in SURVEY.md: LifecycleComponent / CompositeLifecycleStep, used in every
service, e.g. DecodedEventsPipeline.java:122-187). The TPU build's host side
(receivers, connectors, schedulers, API server) keeps that contract — errors
mark a component FAILED instead of crashing the engine, matching the
reference's non-required-step semantics (EventSourcesManager.java:86-88).
"""

from __future__ import annotations

import asyncio
import enum
import logging

logger = logging.getLogger(__name__)


class LifecycleStatus(enum.Enum):
    STOPPED = "stopped"
    INITIALIZING = "initializing"
    INITIALIZED = "initialized"
    STARTING = "starting"
    STARTED = "started"
    STOPPING = "stopping"
    TERMINATED = "terminated"
    FAILED = "failed"


class LifecycleComponent:
    """Base host component with async lifecycle and nested children."""

    def __init__(self, name: str | None = None, required: bool = True):
        self.name = name or type(self).__name__
        self.required = required
        self.status = LifecycleStatus.STOPPED
        self.error: Exception | None = None
        self.children: list["LifecycleComponent"] = []

    def add_child(self, child: "LifecycleComponent") -> "LifecycleComponent":
        self.children.append(child)
        return child

    # subclass hooks -------------------------------------------------------
    async def on_initialize(self) -> None: ...

    async def on_start(self) -> None: ...

    async def on_stop(self) -> None: ...

    # drivers --------------------------------------------------------------
    async def _guard(self, phase: str, status: LifecycleStatus,
                     final: LifecycleStatus, fn, children_first: bool) -> None:
        self.status = status
        try:
            if children_first:
                for c in self.children:
                    await getattr(c, phase)()
                await fn()
            else:
                await fn()
                for c in self.children:
                    await getattr(c, phase)()
            self.status = final
        except Exception as e:
            self.error = e
            self.status = LifecycleStatus.FAILED
            logger.exception("%s %s failed", self.name, phase)
            if self.required:
                raise

    async def initialize(self) -> None:
        await self._guard("initialize", LifecycleStatus.INITIALIZING,
                          LifecycleStatus.INITIALIZED, self.on_initialize, False)

    async def start(self) -> None:
        await self._guard("start", LifecycleStatus.STARTING,
                          LifecycleStatus.STARTED, self.on_start, False)

    async def stop(self) -> None:
        await self._guard("stop", LifecycleStatus.STOPPING,
                          LifecycleStatus.STOPPED, self.on_stop, True)

    async def run_lifespan(self) -> None:
        await self.initialize()
        await self.start()

    def describe(self) -> dict:
        return {
            "name": self.name,
            "status": self.status.value,
            "error": str(self.error) if self.error else None,
            "children": [c.describe() for c in self.children],
        }
