"""Long-term event retention: host-side spill of HBM ring segments to disk.

The reference retains FULL event history in an external time-series store
(InfluxDB/Cassandra/Warp10) and serves arbitrary date-range queries
(service-event-management/.../influxdb/InfluxDbDeviceEventManagement.java:63-161);
the HBM ring (core/store.py) is a fixed-capacity recency window. This module
is the retention tier between them: before a ring row can be overwritten,
its segment is spilled to an on-disk columnar file, and the engines'
``query_events`` transparently merges ring + archive so date ranges older
than the ring come back exactly like the reference's unbounded history.

Design (TPU-first):
- Spooling reads the ring with the SAME ``read_range`` program every time
  (fixed ``segment_rows`` chunk -> one compiled executable, no recompiles)
  and only at flush boundaries, never per event.
- A partition is one (shard, arena) sub-ring: spill order within a
  partition is the ring's write order, so a partition's segments tile
  absolute positions [0, spilled) contiguously.
- Segment files are columnar ``.npz`` (structure-of-arrays, like the ring
  itself). Every segment carries STATISTICS written at append time —
  per-column zone maps (min/max over valid rows for the time + id
  columns) and compact tenant/device/assignment bloom filters — persisted
  in the manifest and mirrored as small members inside the ``.npz``
  itself, so index rebuilds never decompress full columns and queries
  prune whole segments before touching rows (the archive analog of a
  time-series store's shard index + SSTable bloom filters).
- Queries PUSH DOWN: a :class:`SegmentPlanner` evaluates each predicate
  set against the zone maps + blooms and hands back only surviving
  segments newest-first; decoding stops early once the result page is
  provably complete, and only the columns the query touches are
  materialized. Results stay byte-identical to the full scan
  (:meth:`EventArchive.query_unpruned` keeps the unpruned reference
  implementation as the parity oracle).
- Crash safety: segments are written to a temp name and renamed; the
  manifest is rebuilt from the segment files when missing or stale; a
  truncated/corrupt segment file is QUARANTINED (renamed ``*.corrupt``)
  instead of aborting recovery — at index rebuild for files the
  manifest missed, and at first decode for files an intact manifest
  vouched for (rot behind the stats fast path), so one bad file never
  takes the read path down either way.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import pathlib
import zipfile
import zlib

import numpy as np

_COLUMNS = ("etype", "device", "assignment", "tenant", "area", "customer",
            "asset", "ts_ms", "received_ms", "values", "vmask", "aux",
            "valid")

# columns with zone maps (min/max over VALID rows). ``aux0``/``aux1`` are
# the two lanes of the 2-d ``aux`` column (the invocation/alternate-id
# lanes the query surface filters on).
_ZONE_COLUMNS = ("ts_ms", "received_ms", "etype", "device", "assignment",
                 "tenant", "area", "customer")
# columns that additionally carry a bloom filter: the high-cardinality id
# lanes where a min/max interval is too loose to prune (a segment touching
# devices {3, 9000} has a zone map spanning every device in between)
_BLOOM_COLUMNS = ("tenant", "device", "assignment")
_BLOOM_BITS = 1024                     # 128 bytes per column per segment
_BLOOM_WORDS = _BLOOM_BITS // 64
# everything stats computation needs (all predicate columns + validity) —
# deliberately NOT the payload columns (values/vmask), so a lazy backfill
# never decompresses the wide float lanes
_STATS_COLUMNS = ("valid", "ts_ms", "received_ms", "etype", "device",
                  "assignment", "tenant", "area", "customer", "aux")


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer (vectorized) — the bloom hash kernel."""
    with np.errstate(over="ignore"):
        x = x + np.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return x ^ (x >> np.uint64(31))


_BLOOM_SALTS = (np.uint64(0x51_7C_C1_B7_27_22_0A_95),
                np.uint64(0x2545F4914F6CDD1D))


def _bloom_build(vals: np.ndarray) -> np.ndarray:
    """k=2 bloom bitset (uint64[_BLOOM_WORDS]) over integer column values.
    No false negatives by construction — the planner may only ever prune a
    segment the value provably never touched."""
    bits = np.zeros(_BLOOM_WORDS, np.uint64)
    if vals.size:
        v = vals.astype(np.int64).astype(np.uint64)
        for salt in _BLOOM_SALTS:
            h = _mix64(v ^ salt) % np.uint64(_BLOOM_BITS)
            np.bitwise_or.at(bits, (h >> np.uint64(6)).astype(np.int64),
                             np.uint64(1) << (h & np.uint64(63)))
    return bits


def _bloom_positions(value: int) -> list[tuple[int, np.uint64]]:
    """(word index, bit mask) pairs a value sets — shared by the scalar
    membership test and the planner's vectorized matrix test."""
    v = np.uint64(np.int64(value))
    out = []
    for salt in _BLOOM_SALTS:
        h = int(_mix64(np.asarray([v ^ salt], np.uint64))[0]) % _BLOOM_BITS
        out.append((h >> 6, np.uint64(1) << np.uint64(h & 63)))
    return out


def _compute_stats(cols: dict) -> dict:
    """Per-segment statistics over the VALID rows: zone maps for the
    time/id columns, blooms for the high-cardinality ids, and the valid
    row count (lets a provably-full-match segment contribute its total
    without being decoded at all). JSON-serializable (manifest round
    trip); blooms are hex-encoded little-endian uint64 words."""
    valid = np.asarray(cols["valid"], bool)
    idx = np.nonzero(valid)[0]
    st: dict = {"rows": int(idx.size), "z": {}, "bloom": {}}
    if not idx.size:
        return st
    for c in _ZONE_COLUMNS:
        v = np.asarray(cols[c])[idx]
        st["z"][c] = [int(v.min()), int(v.max())]
    aux = np.asarray(cols["aux"])[idx]
    st["z"]["aux0"] = [int(aux[:, 0].min()), int(aux[:, 0].max())]
    st["z"]["aux1"] = [int(aux[:, 1].min()), int(aux[:, 1].max())]
    for c in _BLOOM_COLUMNS:
        st["bloom"][c] = _bloom_build(
            np.asarray(cols[c])[idx]).tobytes().hex()
    return st


# --------------------------------------------------------------- codecs
# Per-column compression for spilled segments (PR-8 leftover). A
# compressed segment stores ``<col>__packed`` uint8 blobs plus one
# ``codec_json`` member instead of the plain column members; the scalar
# stats members (seg_nrows/seg_ts_min/seg_ts_max/stats_json) stay plain,
# so index rebuilds and the planner never touch a codec. Decoding is
# exact (bit-for-bit round trip, pinned in tests): integer columns are
# delta-coded along axis 0, zigzagged, packed to the minimal uint width
# and deflated; bool columns packbits + deflate; float payloads deflate
# raw. All stdlib — no new dependencies.

_PACK_WIDTHS = ((np.uint8, 0xFF), (np.uint16, 0xFFFF),
                (np.uint32, 0xFFFFFFFF))


def _encode_column(a: np.ndarray) -> tuple[np.ndarray, dict]:
    """(uint8 blob, meta) for one column. Meta is JSON-serializable and
    self-contained: kind + dtype + shape (+ pack width for ints)."""
    a = np.ascontiguousarray(a)
    meta: dict = {"dtype": str(a.dtype), "shape": list(a.shape)}
    if a.dtype == np.bool_:
        meta["kind"] = "bits"
        raw = np.packbits(a.reshape(-1)).tobytes()
    elif np.issubdtype(a.dtype, np.integer):
        meta["kind"] = "delta"
        v = a.astype(np.int64)
        d = np.empty_like(v)
        d[:1] = v[:1]
        if v.shape[0] > 1:
            d[1:] = v[1:] - v[:-1]
        with np.errstate(over="ignore"):
            u = (d.astype(np.uint64) << np.uint64(1)) \
                ^ (d >> np.int64(63)).astype(np.uint64)
        hi = int(u.max()) if u.size else 0
        for w, cap in _PACK_WIDTHS:
            if hi <= cap:
                u = u.astype(w)
                break
        meta["width"] = u.dtype.itemsize
        raw = u.tobytes()
    else:
        meta["kind"] = "raw"
        raw = a.tobytes()
    blob = np.frombuffer(zlib.compress(raw, 6), np.uint8)
    return blob, meta


def _decode_column(blob: np.ndarray, meta: dict) -> np.ndarray:
    """Exact inverse of :func:`_encode_column`."""
    raw = zlib.decompress(np.ascontiguousarray(blob).tobytes())
    dtype = np.dtype(meta["dtype"])
    shape = tuple(meta["shape"])
    kind = meta["kind"]
    if kind == "bits":
        n = int(np.prod(shape)) if shape else 1
        return np.unpackbits(np.frombuffer(raw, np.uint8),
                             count=n).astype(bool).reshape(shape)
    if kind == "delta":
        w = np.dtype(f"uint{8 * int(meta['width'])}")
        u = np.frombuffer(raw, w).astype(np.uint64)
        d = ((u >> np.uint64(1))
             ^ (np.uint64(0) - (u & np.uint64(1)))).astype(np.int64)
        d = d.reshape(shape)
        with np.errstate(over="ignore"):
            v = np.cumsum(d, axis=0, dtype=np.int64) if d.size else d
        return v.astype(dtype)
    return np.frombuffer(raw, dtype).reshape(shape)


def _segment_members(part: int, start: int, topology: "str | None",
                     cols: dict, count: int, ts_min: int, ts_max: int,
                     stats: dict, compress: bool) -> tuple[dict, dict]:
    """The np.savez member dict for one segment file (shared by
    :meth:`EventArchive.append_segment` and :meth:`EventArchive.compact`)
    plus the stats dict as persisted — stats gain ``bytes`` (decoded
    column bytes) and ``enc_bytes`` (on-disk encoded bytes), the
    planner's decompression-cost inputs."""
    raw_bytes = int(sum(np.asarray(v).nbytes for v in cols.values()))
    members: dict = {"part": np.int64(part), "start": np.int64(start),
                     "topology": np.str_(topology or ""),
                     "seg_nrows": np.int64(count),
                     "seg_ts_min": np.int64(ts_min),
                     "seg_ts_max": np.int64(ts_max)}
    if compress:
        codec: dict = {}
        enc = 0
        for c in _COLUMNS:
            blob, meta = _encode_column(np.asarray(cols[c]))
            members[c + "__packed"] = blob
            codec[c] = meta
            enc += int(blob.nbytes)
        members["codec_json"] = np.str_(json.dumps(codec))
        stats = dict(stats, bytes=raw_bytes, enc_bytes=enc)
    else:
        members.update(cols)
        stats = dict(stats, bytes=raw_bytes, enc_bytes=raw_bytes)
    members["stats_json"] = np.str_(json.dumps(stats))
    return members, stats


def mesh_topology(n_shards: int, arenas: int) -> str:
    """Canonical topology stamp of a mesh engine's archive — ONE producer
    for the stamp the engine writes, recovery matches, and migration
    rewrites."""
    return f"mesh/{n_shards}x{arenas}"


def single_topology(arenas: int) -> str:
    """Canonical topology stamp of a single-chip engine's archive."""
    return f"single/{arenas}"


@dataclasses.dataclass
class _Segment:
    part: int        # partition = shard * arenas + arena (0 for 1-ring)
    start: int       # absolute position of first row within the partition
    count: int
    ts_min: int
    ts_max: int
    path: str
    stats: dict | None = None   # zone maps + blooms + valid-row count;
                                # None on manifests written before the
                                # pushdown tier (back-filled lazily)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class SegmentCache:
    """Bounded LRU of decoded segment columns, keyed by segment path.

    Columns load LAZILY: predicate evaluation pulls only the columns the
    query touches (npz members decompress individually) and the row
    materialization that follows reuses the same entry. Shared by the
    planner-driven query path, by-id lookups (``get_row``), chunked replay
    (``read_rows``), and compaction, so none of them re-``np.load`` a file
    another caller just decoded. Entries die with their segment (expiry,
    compaction, retire, quarantine) via :meth:`retain`."""

    def __init__(self, max_segments: int = 8):
        self.max_segments = max(1, int(max_segments))
        self._entries: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()
        self.hits = 0      # calls fully served from cache
        self.loads = 0     # np.load file opens (misses, counted per open)

    @property
    def nbytes(self) -> int:
        """Host bytes held by decoded segment columns — the memory
        ledger's segment-cache component (ISSUE 11). Counted at RESIDENT
        (decoded) size: a column decoded from a compressed segment costs
        its full numpy footprint, not its on-disk encoded size, so
        ``devicewatch_ledger_reconciles`` stays a true gate (ISSUE 19
        satellite); raw byte buffers are counted by length."""
        total = 0
        for entry in self._entries.values():
            for col in entry.values():
                if hasattr(col, "nbytes"):
                    total += int(col.nbytes)
                elif isinstance(col, (bytes, bytearray, memoryview)):
                    total += len(col)
        return total

    def columns(self, directory: pathlib.Path, path: str,
                names: tuple) -> dict:
        entry = self._entries.get(path)
        if entry is not None:
            self._entries.move_to_end(path)
            missing = [c for c in names if c not in entry]
            if not missing:
                self.hits += 1
                return entry
        else:
            missing = list(names)
        with np.load(directory / path) as z:
            fresh = {}
            codec = None
            for c in missing:
                if c in z.files:
                    fresh[c] = np.asarray(z[c])
                    continue
                # compressed segment: the plain member is absent and the
                # column decodes from its packed blob — the ONE hook all
                # read paths (query/get_row/read_rows/compact) share, so
                # decoded columns land in the cache at resident size
                if codec is None:
                    codec = json.loads(str(z["codec_json"]))
                fresh[c] = _decode_column(np.asarray(z[c + "__packed"]),
                                          codec[c])
        self.loads += 1
        if entry is None:
            entry = self._entries[path] = {}
            self._entries.move_to_end(path)
            while len(self._entries) > self.max_segments:
                self._entries.popitem(last=False)
        entry.update(fresh)
        return entry

    def retain(self, live_paths: set) -> None:
        for p in list(self._entries):
            if p not in live_paths:
                del self._entries[p]


class SegmentPlanner:
    """Zone-map + bloom pruning over an archive's segment index.

    The planner keeps VECTORIZED per-column tables (one numpy row per
    segment, rebuilt only when the index generation changes), so Q
    concurrent queries in a batcher round share one planning pass: each
    predicate set reduces to a handful of numpy comparisons over the
    whole index instead of a per-segment Python loop. For every query it
    returns the surviving segments NEWEST-FIRST (by their valid-rows
    ts upper bound) together with a provably-full-match flag: a segment
    whose zone maps prove that EVERY valid row matches (and whose
    eviction cap covers it) can contribute its stored row count without
    being decoded at all once the result page is closed.

    Pruning is exact, never lossy: zone maps bound the valid rows, blooms
    have no false negatives, and a surviving segment still evaluates the
    full row-level mask — a bloom false positive costs one decode, never
    a wrong row."""

    _BIG = np.int64(2**62)

    def __init__(self, archive: "EventArchive"):
        self.archive = archive
        self._gen = -1
        # planning passes served (one per plan()/plan_batch() call, NOT
        # per predicate set): the batcher round batches its Q archive
        # requests into ONE call, so calls per round must be exactly 1 —
        # exported as swtpu_archive_planner_calls_total and pinned by
        # tests/test_archive_pushdown.py
        self.calls = 0

    # ---------------------------------------------------------- tables
    def _refresh(self) -> None:
        arch = self.archive
        if self._gen == arch._generation:
            return
        # capture the generation BEFORE snapshotting: if a concurrent
        # append lands mid-build we record the OLD generation, so the
        # next plan() rebuilds and sees the tail (never a stale table
        # stamped with a fresh generation)
        gen = arch._generation
        # lazy back-fill: segments adopted from a pre-pushdown manifest
        # carry no stats; compute them once (predicate columns only) and
        # persist, so the cost is paid on first plan, not every plan
        dirty = False
        # snapshot: back-fill can QUARANTINE an unreadable segment,
        # which removes it from arch.segments mid-walk
        for s in list(arch.segments):
            if s.stats is None:
                arch._ensure_stats(s)
                dirty = True
        if dirty:
            arch._save_index()
        # snapshot AGAIN: a concurrent spool (analytics job planning
        # while the ingest thread appends segments) must not grow the
        # list under the array builds below — the fresh tail is picked
        # up by the next generation bump
        segs = list(arch.segments)     # (part, start)-sorted == scan order
        n = len(segs)
        self._segs = segs
        self._part = np.fromiter((s.part for s in segs), np.int64, n)
        self._start = np.fromiter((s.start for s in segs), np.int64, n)
        self._count = np.fromiter((s.count for s in segs), np.int64, n)
        self._rows = np.fromiter(
            ((s.stats or {}).get("rows", -1) for s in segs), np.int64, n)
        known = self._rows >= 0
        self._known = known
        self._z = {}
        for c in _ZONE_COLUMNS + ("aux0", "aux1"):
            zmin = np.full(n, -self._BIG)
            zmax = np.full(n, self._BIG)
            for i, s in enumerate(segs):
                z = (s.stats or {}).get("z", {}).get(c)
                if z is not None:
                    zmin[i], zmax[i] = z
                elif known[i]:
                    # known stats with no zone entry = zero valid rows:
                    # an empty interval fails every predicate
                    zmin[i], zmax[i] = self._BIG, -self._BIG
            self._z[c] = (zmin, zmax)
        # newest-first bound on VALID rows' event time; unknown-stats
        # segments fall back to the all-rows bound (still an upper bound)
        zts_min, zts_max = self._z["ts_ms"]
        all_hi = np.fromiter((s.ts_max for s in segs), np.int64, n)
        all_lo = np.fromiter((s.ts_min for s in segs), np.int64, n)
        self._ts_hi = np.where(known, np.minimum(zts_max, all_hi), all_hi)
        self._ts_lo = np.where(known & (self._rows > 0),
                               np.maximum(zts_min, all_lo), all_lo)
        self._bloom = {}
        for c in _BLOOM_COLUMNS:
            mat = np.full((n, _BLOOM_WORDS), np.uint64(0xFFFFFFFFFFFFFFFF),
                          np.uint64)     # unknown = all bits = never prunes
            for i, s in enumerate(segs):
                h = (s.stats or {}).get("bloom", {}).get(c)
                if h is not None:
                    mat[i] = np.frombuffer(bytes.fromhex(h), np.uint64)
                elif known[i]:
                    mat[i] = 0           # zero valid rows: nothing matches
            self._bloom[c] = mat
        # per-segment decode-cost table (ISSUE 19): resident column bytes
        # plus, for compressed segments, the encoded bytes that must flow
        # through the codec — so a round packer budgeting by cost charges
        # decompression, not just materialization. Segments written
        # before cost stats existed fall back to a per-row estimate.
        self._cost = np.empty(n, np.int64)
        for i, s in enumerate(segs):
            st = s.stats or {}
            if "bytes" in st:
                self._cost[i] = (int(st["bytes"])
                                 + int(st.get("enc_bytes", st["bytes"])))
            else:
                self._cost[i] = s.count * 128
        self._gen = gen

    def cost_of(self, scan_order: int) -> int:
        """Decode cost (bytes) of the segment a plan row named by its
        ``scan_order`` index — valid until the index generation moves,
        i.e. for the plan the caller just received."""
        self._refresh()
        return int(self._cost[scan_order])

    # ------------------------------------------------------------ plan
    def plan(self, *, max_pos=None, device=None, etype=None, tenant=None,
             assignment=None, aux0=None, aux1=None, area=None,
             customer=None, since_ms=None, until_ms=None,
             device_parts=None, assignment_parts=None):
        """One predicate set -> ``(rows, considered)`` where ``rows`` is a
        newest-first list of ``(scan_order, segment, full_match, ts_hi,
        cap_covers)`` tuples and ``considered`` counts the segments the
        eviction cap admitted (what an unpruned scan would have opened)."""
        self.calls += 1
        self._refresh()
        return self._plan_refreshed(
            max_pos=max_pos, device=device, etype=etype, tenant=tenant,
            assignment=assignment, aux0=aux0, aux1=aux1, area=area,
            customer=customer, since_ms=since_ms, until_ms=until_ms,
            device_parts=device_parts, assignment_parts=assignment_parts)

    def plan_batch(self, requests: list, *, max_pos=None) -> list:
        """Evaluate N predicate sets in ONE planner call (ISSUE 10
        satellite): the table refresh — the expensive half when the index
        generation moved (stats back-fill, vectorized column tables) —
        runs once for the whole batch, and ``calls`` counts the batch as
        a single planning pass. ``requests`` are filter-kwarg dicts (the
        keys :meth:`plan` accepts, minus ``max_pos``, which is shared —
        one batcher round has one snapshot cursor capture). Returns one
        ``(rows, considered)`` per request, each identical to what a
        standalone :meth:`plan` would return."""
        self.calls += 1
        self._refresh()
        return [self._plan_refreshed(max_pos=max_pos, **req)
                for req in requests]

    def _plan_refreshed(self, *, max_pos=None, device=None, etype=None,
                        tenant=None, assignment=None, aux0=None, aux1=None,
                        area=None, customer=None, since_ms=None,
                        until_ms=None, device_parts=None,
                        assignment_parts=None):
        n = len(self._segs)
        if not n:
            return [], 0
        if max_pos is not None:
            caps = np.fromiter((max_pos.get(int(p), 0) for p in self._part),
                               np.int64, n)
            eligible = self._start < caps
            cap_covers = caps >= self._start + self._count
        else:
            eligible = np.ones(n, bool)
            cap_covers = np.ones(n, bool)
        considered = int(eligible.sum())
        alive = eligible.copy()
        # a known-empty segment (zero valid rows) contributes nothing
        alive &= ~self._known | (self._rows > 0)
        full = alive & self._known & (self._rows > 0) & cap_covers

        def eq(col: str, v) -> None:
            nonlocal alive, full
            if v is None:
                return
            v = int(v)
            zmin, zmax = self._z[col]
            alive &= (zmin <= v) & (v <= zmax)
            full &= (zmin == v) & (zmax == v)
            mat = self._bloom.get(col)
            if mat is not None:
                hit = np.ones(n, bool)
                for w, mask in _bloom_positions(v):
                    hit &= (mat[:, w] & mask) != 0
                alive &= hit

        eq("device", device)
        eq("etype", etype)
        eq("tenant", tenant)
        eq("assignment", assignment)
        eq("aux0", aux0)
        eq("aux1", aux1)
        eq("area", area)
        eq("customer", customer)
        if since_ms is not None:
            alive &= self._ts_hi >= int(since_ms)
            full &= self._ts_lo >= int(since_ms)
        if until_ms is not None:
            alive &= self._ts_lo <= int(until_ms)
            full &= self._ts_hi <= int(until_ms)
        # shard-scoped id namespaces (mesh): a filter bound to one shard's
        # partitions contributes zero rows everywhere else
        if device is not None and device_parts is not None:
            alive &= np.isin(self._part, list(device_parts))
        if assignment is not None and assignment_parts is not None:
            alive &= np.isin(self._part, list(assignment_parts))
        order = np.nonzero(alive)[0]
        if order.size:
            order = order[np.lexsort((order, -self._ts_hi[order]))]
        return ([(int(i), self._segs[i], bool(full[i]),
                  int(self._ts_hi[i]), bool(cap_covers[i]))
                 for i in order], considered)


class EventArchive:
    """Directory of spilled ring segments + a queryable index.

    A partition is one independent sub-ring feeding this archive (an
    arena for a single-chip engine; (shard, arena) flattened for the
    mesh); each keeps its own spill watermark. ``topology`` labels the
    exact engine shape writing the archive (see __init__)."""

    def __init__(self, directory: str | pathlib.Path, segment_rows: int = 4096,
                 max_rows_per_part: int | None = None,
                 topology: str | None = None,
                 max_age_ms: int | None = None,
                 cache_segments: int = 8,
                 compress: bool = False):
        self.dir = pathlib.Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.segment_rows = int(segment_rows)
        # per-column compression for NEWLY written segments (existing
        # files are read as-is either way — the decode hook keys off each
        # file's own members, so mixed archives work)
        self.compress = bool(compress)
        # partition-topology stamp: segment `part` indices are only
        # meaningful for the exact engine layout that wrote them — after an
        # elastic reshard (or a single<->mesh migration with equal
        # partition COUNTS) the same integers would resolve to the WRONG
        # shard/arena and shard-local device ids shift, so the stamp is a
        # full shape label (e.g. "mesh/8x1"), and any mismatch retires the
        # old data instead of misreading it
        self.topology = topology
        # retention policy (reference: per-assignment
        # INFLUX_RETENTION_POLICY override, InfluxDbDeviceEventManagement):
        # None = unbounded history; otherwise each partition keeps at most
        # this many archived rows and the OLDEST whole segments expire.
        # The newest archived rows duplicate the ring window (spill is
        # eager), so the queryable history beyond the ring is roughly
        # max_rows_per_part - arena_capacity: size the cap ABOVE the ring
        self.max_rows_per_part = max_rows_per_part
        # time-based retention (the closer Influx analog): a segment whose
        # NEWEST event is older than the partition's newest event minus
        # max_age_ms expires wholesale. Event-time based (ts_ms domain),
        # so replayed/backfilled history ages consistently
        self.max_age_ms = max_age_ms
        self.expired_rows = 0
        self.segments: list[_Segment] = []
        self.lost_rows = 0   # rows overwritten before they could spill
        # per-partition segments sorted by start (bisect lookups) + the
        # LRU segment-decode cache shared by queries, by-id lookups and
        # chunked replay (one decode per segment per working set, not per
        # call)
        self._by_part: dict[int, list[_Segment]] = {}
        self.cache = SegmentCache(max_segments=cache_segments)
        # monotone spill watermark per partition, independent of segment
        # PRESENCE: retention may expire the tail segment (backfilled event
        # times), and a watermark derived from surviving segments would
        # regress below the ring head — making the spooler re-spill and
        # re-expire the same rows forever
        self._spilled: dict[int, int] = {}
        # registered gaps: position ranges that NEVER held data (topology
        # migration pads history up to an arena boundary) — replay must
        # not count them as lost rows
        self._gaps: dict[int, list[list[int]]] = {}
        # pushdown accounting (exported as swtpu_archive_* gauges at
        # scrape time; the bench's pruning proof reads them directly)
        self.queries = 0            # pushdown query() calls
        self.plan_considered = 0    # segments the eviction cap admitted
        self.plan_pruned = 0        # ...of which zone maps/blooms pruned
        self.plan_decoded = 0       # unique segments decoded per query
        self.count_shortcuts = 0    # full-match segments counted w/o decode
        self.corrupt_segments = 0   # files quarantined (rebuild or decode)
        self._generation = 0        # bumped on every index mutation; the
                                    # planner rebuilds its tables on change
        self._planner = SegmentPlanner(self)
        self._load_index()

    # ------------------------------------------------------------- index
    def _manifest_path(self) -> pathlib.Path:
        return self.dir / "index.json"

    def _load_index(self) -> None:
        # a crash mid-write leaves a *.npz.tmp — never adopted (the glob
        # below requires the final .npz name), just swept away here
        for stray in self.dir.glob("*.npz.tmp"):
            stray.unlink()
        manifest = self._manifest_path()
        known: dict[str, _Segment] = {}
        if manifest.exists():
            m = json.loads(manifest.read_text())
            stamped = m.get("topology", m.get("parts"))
            if (self.topology is not None and stamped is not None
                    and str(stamped) != self.topology):
                self._retire(str(stamped))
            else:
                for e in m.get("segments", []):
                    known[e["path"]] = _Segment(**e)
                self._spilled = {int(k): int(v)
                                 for k, v in m.get("spilled", {}).items()}
                self._gaps = {int(k): [[int(lo), int(hi)] for lo, hi in v]
                              for k, v in m.get("gaps", {}).items()}
        # adopt any segment file the manifest missed (crash between the
        # segment rename and the manifest rewrite) — but NEVER a file whose
        # own topology stamp disagrees (a manifest-less dir must not smuggle
        # old-topology partition indices past the retire check). A file
        # that cannot be read at all (truncated by a crash, bit rot) is
        # QUARANTINED — renamed aside and counted — so one bad segment
        # never takes the rest of the archive down with it.
        for f in sorted(self.dir.glob("seg-*.npz")):
            if f.name in known:
                self.segments.append(known[f.name])
                continue
            try:
                with np.load(f) as z:
                    # an archive opened with topology=None stamps
                    # np.str_(""); treat that like a missing stamp (same
                    # semantics as a null manifest stamp) so such segments
                    # are adopted, not retired, by a topology-aware open
                    seg_topo = (str(z["topology"]) if "topology" in z.files
                                else "") or None
                    if (self.topology is not None and seg_topo is not None
                            and seg_topo != self.topology):
                        pass  # retired below, outside the np.load handle
                    else:
                        seg_topo = None
                        if "seg_nrows" in z.files:
                            # stats members written at append time: the
                            # rebuild touches only scalars + the compact
                            # stats blob, never a full column
                            count = int(z["seg_nrows"])
                            ts_min = int(z["seg_ts_min"])
                            ts_max = int(z["seg_ts_max"])
                            stats = json.loads(str(z["stats_json"]))
                        else:
                            # pre-pushdown file: full-column fallback and
                            # the lazy stats back-fill in one read
                            ts = z["ts_ms"]
                            count = int(ts.shape[0])
                            ts_min = int(ts.min()) if ts.size else 0
                            ts_max = int(ts.max()) if ts.size else 0
                            stats = _compute_stats(
                                {c: np.asarray(z[c])
                                 for c in _STATS_COLUMNS})
                        self.segments.append(_Segment(
                            part=int(z["part"]), start=int(z["start"]),
                            count=count, ts_min=ts_min, ts_max=ts_max,
                            path=f.name, stats=stats))
            except (OSError, ValueError, KeyError, EOFError,
                    zipfile.BadZipFile) as err:
                self._quarantine(f, err)
                continue
            if seg_topo is not None:
                self._retire(seg_topo, files=[f])
        self.segments.sort(key=lambda s: (s.part, s.start))
        self._drop_covered()
        self._reindex()

    def _quarantine(self, f: pathlib.Path, err: Exception) -> None:
        """Move an unreadable segment file aside (``<name>.corrupt`` —
        outside the ``seg-*.npz`` recovery glob) so the rest of the
        archive keeps serving; the loss is counted and logged LOUDLY, and
        the file is preserved for offline forensics."""
        import logging

        target = f.with_name(f.name + ".corrupt")
        n = 0
        while target.exists():
            n += 1
            target = f.with_name(f"{f.name}.corrupt{n}")
        f.rename(target)
        self.corrupt_segments += 1
        logging.getLogger(__name__).warning(
            "archive: QUARANTINED corrupt segment %s -> %s (%s: %s); "
            "its rows are unavailable until repaired, the rest of the "
            "archive keeps serving", f.name, target.name,
            type(err).__name__, err)

    def _drop_corrupt(self, seg: "_Segment", err: Exception) -> None:
        """Quarantine a segment that failed to DECODE after adoption — a
        manifest-listed file is trusted at :meth:`_load_index` without
        being opened (that's the point of the stats fast path), so
        truncation/bit rot behind an intact manifest only surfaces at
        first decode. The file moves aside, the segment leaves the index
        (generation bump makes planners rebuild), and the caller serves
        on without its rows instead of failing every query that plans
        over it."""
        try:
            self.segments.remove(seg)
        except ValueError:
            return   # already dropped (repeated failure on a stale ref)
        f = self.dir / seg.path
        if f.exists():
            self._quarantine(f, err)
        else:
            self.corrupt_segments += 1   # vanished from under us: still
                                         # counted, nothing to rename
        self._reindex()
        self._save_index()

    def _cols_or_drop(self, seg: "_Segment", names: tuple) -> dict | None:
        """Decode ``names`` columns of ``seg`` via the shared cache;
        an unreadable file is quarantined (:meth:`_drop_corrupt`) and
        ``None`` returned so one rotten segment never takes the whole
        read path down."""
        try:
            return self.cache.columns(self.dir, seg.path, names)
        except (OSError, ValueError, KeyError, EOFError,
                zipfile.BadZipFile) as err:
            self._drop_corrupt(seg, err)
            return None

    def _ensure_stats(self, seg: _Segment) -> None:
        """Back-fill zone maps + blooms for a segment adopted from a
        pre-pushdown manifest (predicate columns only, via the shared
        decode cache). An unreadable segment quarantines instead."""
        cols = self._cols_or_drop(seg, _STATS_COLUMNS)
        if cols is not None:
            seg.stats = _compute_stats(cols)

    def _drop_covered(self) -> None:
        """Delete segment files whose row range is fully covered by a
        larger segment of the same partition — the leftovers of a
        compaction that crashed between the merged-segment rename and the
        source deletes (merged files exactly cover their sources, so
        covered == superseded)."""
        keep: list[_Segment] = []
        end: dict[int, int] = {}
        for s in sorted(self.segments,
                        key=lambda s: (s.part, s.start, -s.count)):
            if s.start + s.count <= end.get(s.part, 0):
                (self.dir / s.path).unlink(missing_ok=True)
                continue
            end[s.part] = max(end.get(s.part, 0), s.start + s.count)
            keep.append(s)
        self.segments = keep

    def _reindex(self) -> None:
        self._by_part = {}
        for s in self.segments:
            self._by_part.setdefault(s.part, []).append(s)
        for segs in self._by_part.values():
            segs.sort(key=lambda s: s.start)
        self._generation += 1
        # decode-cache entries die with their segment (expiry, compaction,
        # retire, quarantine, test surgery on .segments)
        self.cache.retain({s.path for s in self.segments})

    def _retire(self, old_topology: str,
                files: "list[pathlib.Path] | None" = None) -> None:
        """Move different-topology archive files aside (never delete
        history: the operator may migrate it offline). Runs before any
        index adoption, so the live archive never carries them."""
        import logging

        tag = old_topology.replace("/", "-")
        retired = self.dir / f"retired-{tag}"
        n = 0
        while retired.exists():
            n += 1
            retired = self.dir / f"retired-{tag}-{n}"
        retired.mkdir()
        if files is None:
            files = list(self.dir.glob("seg-*.npz")) + [self._manifest_path()]
        for f in files:
            if f.exists():
                f.rename(retired / f.name)
        logging.getLogger(__name__).warning(
            "archive topology changed (%s -> %s): previous history moved "
            "to %s; spill starts fresh",
            old_topology, self.topology, retired)

    def _save_index(self) -> None:
        tmp = self._manifest_path().with_suffix(".tmp")
        tmp.write_text(json.dumps(
            {"topology": self.topology,
             "spilled": self._spilled,
             "gaps": self._gaps,
             "segments": [s.to_json() for s in self.segments]}))
        tmp.replace(self._manifest_path())

    def spilled(self, part: int) -> int:
        """Next absolute position of ``part`` the spooler should write —
        monotone even after retention expires the newest-position
        segment."""
        ends = max((s.start + s.count for s in self._by_part.get(part, ())),
                   default=0)
        return max(self._spilled.get(part, 0), ends)

    def total_rows(self) -> int:
        return sum(s.count for s in self.segments)

    def register_gap(self, part: int, lo: int, hi: int) -> None:
        """Record [lo, hi) of ``part`` as positions that never held data
        (migration padding) — replay skips them without loss accounting."""
        if hi > lo:
            self._gaps.setdefault(part, []).append([int(lo), int(hi)])

    def gap_rows(self, part: int, lo: int, hi: int) -> int:
        """Rows of [lo, hi) covered by registered never-written gaps."""
        return sum(max(0, min(hi, g_hi) - max(lo, g_lo))
                   for g_lo, g_hi in self._gaps.get(part, ()))

    # ------------------------------------------------------------- write
    def append_segment(self, part: int, start: int, sl) -> None:
        """Persist one contiguous ring slice (a ``StoreSlice`` already on
        host). Idempotent: re-spooling an existing (part, start) range —
        e.g. after WAL replay — is a no-op. Zone maps + blooms are
        computed HERE, once, while the columns are already in memory —
        queries and index rebuilds only ever read them back."""
        name = f"seg-p{part:04d}-o{start:014d}-n{sl.ts_ms.shape[0]}.npz"
        path = self.dir / name
        end = start + int(sl.ts_ms.shape[0])
        self._spilled[part] = max(self._spilled.get(part, 0), end)
        if path.exists():
            return
        cols = {c: np.asarray(getattr(sl, c)) for c in _COLUMNS}
        ts = cols["ts_ms"]
        count = int(ts.shape[0])
        ts_min = int(ts.min()) if ts.size else 0
        ts_max = int(ts.max()) if ts.size else 0
        stats = _compute_stats(cols)
        members, stats = _segment_members(
            part, start, self.topology, cols, count, ts_min, ts_max,
            stats, self.compress)
        # temp name must NOT match the seg-*.npz recovery glob (write via a
        # file handle — np.savez would append .npz to a bare path)
        tmp = path.with_name(path.name + ".tmp")
        with open(tmp, "wb") as f:
            np.savez(f, **members)
        tmp.replace(path)
        self.segments.append(_Segment(
            part=part, start=start, count=count,
            ts_min=ts_min, ts_max=ts_max, path=name, stats=stats))
        self.segments.sort(key=lambda s: (s.part, s.start))
        self._reindex()
        self._expire(part)
        self._save_index()

    def _expire(self, part: int) -> None:
        """Apply the retention policies: drop this partition's OLDEST whole
        segments while it exceeds ``max_rows_per_part``, and any segment
        whose newest event fell behind ``max_age_ms`` of the partition's
        newest. Expired rows are deliberate policy (counted separately
        from ``lost_rows``)."""
        if self.max_rows_per_part is None and self.max_age_ms is None:
            return
        segs = self._by_part.get(part, [])
        victims: list[_Segment] = []
        # phase 1 — row cap pops in WRITE order (oldest position first)
        if self.max_rows_per_part is not None:
            total = sum(s.count for s in segs)
            while segs and total > self.max_rows_per_part:
                victims.append(segs.pop(0))
                total -= victims[-1].count
        # phase 2 — age horizon from the SURVIVORS' newest event (a
        # just-popped segment must not inflate it), sweeping EVERY
        # segment: event time is client-supplied, so a backfilled segment
        # can sit behind a fresher one in write order
        if self.max_age_ms is not None and segs:
            horizon = max(s.ts_max for s in segs) - self.max_age_ms
            victims += [s for s in segs if s.ts_max < horizon]
            segs[:] = [s for s in segs if s.ts_max >= horizon]
        for victim in victims:
            self.expired_rows += victim.count
            self.segments.remove(victim)
            (self.dir / victim.path).unlink(missing_ok=True)
        if victims:
            self._reindex()

    # -------------------------------------------------------- maintenance
    def compact(self, target_rows: int | None = None) -> dict:
        """Merge runs of contiguous small segments per partition into
        files of up to ``target_rows`` (default 8x the spool segment) —
        the maintenance the reference delegates to its time-series
        store's own compaction (Influx shard compaction). Row positions
        are preserved, so by-id lookups, replay cursors, and the query
        cap are unaffected. Crash-safe: the merged file is renamed into
        place before the sources are deleted; a crash in between leaves
        covered sources that ``_load_index`` sweeps."""
        target = int(target_rows or 8 * self.segment_rows)
        merged_segments = files_removed = 0
        for part, segs in list(self._by_part.items()):
            i = 0
            while i < len(segs):
                run = [segs[i]]
                total = segs[i].count
                j = i + 1
                while (j < len(segs)
                       and segs[j].start == run[-1].start + run[-1].count
                       and total + segs[j].count <= target):
                    total += segs[j].count
                    run.append(segs[j])
                    j += 1
                if len(run) < 2:
                    i = j
                    continue
                cols: "dict[str, list] | None" = {c: [] for c in _COLUMNS}
                for s in run:
                    sc = self._segment_cols(s)
                    if sc is None:   # quarantined: leave this run alone
                        cols = None
                        break
                    for c in _COLUMNS:
                        cols[c].append(sc[c])
                if cols is None:
                    i = j
                    continue
                merged = {c: np.concatenate(cols[c]) for c in _COLUMNS}
                start = run[0].start
                ts = merged["ts_ms"]
                ts_min = int(ts.min()) if ts.size else 0
                ts_max = int(ts.max()) if ts.size else 0
                stats = _compute_stats(merged)
                members, stats = _segment_members(
                    part, start, self.topology, merged, total, ts_min,
                    ts_max, stats, self.compress)
                name = f"seg-p{part:04d}-o{start:014d}-n{total}.npz"
                tmp = self.dir / (name + ".tmp")
                with open(tmp, "wb") as f:
                    np.savez(f, **members)
                tmp.replace(self.dir / name)
                new_seg = _Segment(
                    part=part, start=start, count=total,
                    ts_min=ts_min, ts_max=ts_max, path=name, stats=stats)
                for s in run:
                    (self.dir / s.path).unlink(missing_ok=True)
                    self.segments.remove(s)
                    files_removed += 1
                self.segments.append(new_seg)
                merged_segments += 1
                segs[i:j] = [new_seg]
                i += 1
        if merged_segments:
            self.segments.sort(key=lambda s: (s.part, s.start))
            self._reindex()
            self._save_index()
        return {"merged_segments": merged_segments,
                "files_removed": files_removed,
                "files_now": len(self.segments)}

    def disk_usage(self) -> dict:
        """Bytes on disk: live segments + everything under retired-*/
        (the disk-bounding observability knob). Tolerates concurrent
        expiry/compaction unlinking files mid-walk."""
        live = 0
        segments = list(self.segments)
        for s in segments:
            try:
                live += (self.dir / s.path).stat().st_size
            except FileNotFoundError:
                pass
            except OSError:
                pass
        retired = retired_files = 0
        for d in self.dir.glob("retired-*"):
            for f in d.rglob("*"):
                try:
                    if f.is_file():
                        retired += f.stat().st_size
                        retired_files += 1
                except OSError:
                    pass
        return {"live_bytes": live, "live_segments": len(segments),
                "retired_bytes": retired, "retired_files": retired_files}

    def purge_retired(self) -> int:
        """Delete every retired-*/ directory (call AFTER their history has
        been migrated to the new topology — reshard_snapshot's archive
        migration — or is otherwise expendable). Returns bytes
        reclaimed."""
        import shutil

        freed = 0
        for d in self.dir.glob("retired-*"):
            for f in d.rglob("*"):
                if f.is_file():
                    freed += f.stat().st_size
            shutil.rmtree(d)
        return freed

    def note_lost(self, count: int) -> None:
        """Record rows that wrapped before spooling (mis-sized trigger —
        surfaced in metrics the way the feed reports ``lag_lost``)."""
        self.lost_rows += int(count)

    # ------------------------------------------------------------- query
    def get_row(self, part: int, pos: int) -> dict | None:
        """Fetch one archived row by (partition, absolute position) — the
        by-id lookup for events evicted from the ring. Returns the ring
        column layout as a dict, or None if the position was never
        spilled."""
        seg = self._segment_for(part, pos)
        if seg is None:
            return None
        cols = self._segment_cols(seg)
        if cols is None:
            return None
        i = pos - seg.start
        if not bool(cols["valid"][i]):
            return None
        return {c: cols[c][i] for c in _COLUMNS}

    def _segment_for(self, part: int, pos: int) -> "_Segment | None":
        import bisect

        segs = self._by_part.get(part)
        if not segs:
            return None
        i = bisect.bisect_right(segs, pos, key=lambda s: s.start) - 1
        if i >= 0 and segs[i].start <= pos < segs[i].start + segs[i].count:
            return segs[i]
        return None

    def next_start(self, part: int, pos: int) -> int | None:
        """First archived position strictly after ``pos`` that is on disk
        — where replay resumes after a recorded-loss gap."""
        import bisect

        segs = self._by_part.get(part)
        if not segs:
            return None
        i = bisect.bisect_right(segs, pos, key=lambda s: s.start)
        return segs[i].start if i < len(segs) else None

    def _segment_cols(self, seg: "_Segment") -> dict | None:
        return self._cols_or_drop(seg, _COLUMNS)

    def read_rows(self, part: int, start: int, count: int):
        """Contiguous archived rows [start, start+n) of a partition as a
        StoreSlice-compatible column namespace (n <= count; one segment per
        call — callers loop). Returns (cols, n); n == 0 means the range is
        not on disk (never spilled, or a recorded-loss gap — see
        :meth:`next_start`). Bisect lookup + the shared LRU decode cache,
        so chunked replay never rescans the index or re-extracts a segment
        file."""
        import types

        seg = self._segment_for(part, start)
        if seg is None:
            return None, 0
        i = start - seg.start
        n = min(count, seg.count - i)
        cols = self._segment_cols(seg)
        if cols is None:
            return None, 0
        return types.SimpleNamespace(
            **{c: cols[c][i:i + n] for c in _COLUMNS}), n

    def query(self, *, max_pos: dict[int, int] | None = None,
              device: int | None = None, etype: int | None = None,
              tenant: int | None = None, since_ms: int | None = None,
              until_ms: int | None = None, assignment: int | None = None,
              aux0: int | None = None, aux1: int | None = None,
              area: int | None = None, customer: int | None = None,
              limit: int = 100,
              device_parts: frozenset[int] | None = None,
              assignment_parts: frozenset[int] | None = None,
              ) -> tuple[int, list[dict]]:
        """Newest-first filtered scan over archived rows, with PUSHDOWN.

        The :class:`SegmentPlanner` evaluates the predicate set against
        every segment's zone maps + blooms first; only survivors are
        decoded (newest-first), the scan stops materializing candidates
        once the page is provably complete, provably-full-match segments
        contribute their stored row count without being decoded at all,
        and only the columns the query touches load from disk — the final
        page winners are the only rows whose payload columns materialize.
        Results (total AND rows, ts-tie ordering included) are
        byte-identical to :meth:`query_unpruned`, the retained full-scan
        reference — pinned by tests/test_archive_pushdown.py and the
        smoke-bench archive gate.

        ``max_pos[part]`` caps the scan at rows already EVICTED from that
        partition's ring (absolute position < max_pos) so ring + archive
        results never overlap. ``device_parts``/``assignment_parts`` scope
        a shard-LOCAL id filter to the partitions of its owning shard (mesh
        engines — the id namespaces repeat per shard). Returns
        (total_matching, top rows) where each row is a plain dict of
        scalars/arrays in ring column layout plus ``part``/``pos``.

        Implementation: a one-request :meth:`query_batch` — the batched
        entry point is the product path (one planner call per batcher
        round); this wrapper keeps the historical signature for direct
        callers (DistributedEngine._merge_archive, tests, the oracle
        parity matrix)."""
        return self.query_batch(
            [{"limit": limit, "filters": dict(
                device=device, etype=etype, tenant=tenant,
                assignment=assignment, aux0=aux0, aux1=aux1, area=area,
                customer=customer, since_ms=since_ms, until_ms=until_ms,
                device_parts=device_parts,
                assignment_parts=assignment_parts)}],
            max_pos=max_pos)[0]

    @property
    def planner(self) -> SegmentPlanner:
        """The shared planner — the analytics driver (models/analytics)
        plans its streaming rounds through the same vectorized tables the
        query path uses, cost accounting included."""
        return self._planner

    @property
    def planner_calls(self) -> int:
        """Planning passes served (shared-table evaluations, one per
        plan/plan_batch call) — the swtpu_archive_planner_calls_total
        source; a batcher round contributes exactly 1."""
        return self._planner.calls

    def query_batch(self, requests: list, *,
                    max_pos: dict[int, int] | None = None) -> list:
        """Serve N pushdown queries against ONE planner call (ISSUE 10
        satellite — the PR-8 follow-up): each request is ``{"limit": n,
        "filters": {...}}`` in :class:`SegmentPlanner` filter-kwarg shape,
        all sharing one eviction-cap capture (``max_pos`` — the batcher
        round snapshots cursors once). Per-request results are
        byte-identical to a standalone :meth:`query` with the same
        arguments (pinned in tests/test_archive_pushdown.py); segment
        decodes still dedupe across requests through the LRU
        :class:`SegmentCache`."""
        plans = self._planner.plan_batch(
            [r["filters"] for r in requests], max_pos=max_pos)
        out = []
        for req, (plan_rows, considered) in zip(requests, plans):
            self.queries += 1
            self.plan_considered += considered
            self.plan_pruned += considered - len(plan_rows)
            out.append(self._scan_planned(
                plan_rows, max_pos, max(0, int(req["limit"])),
                req["filters"]))
        return out

    def _scan_planned(self, plan_rows: list, max_pos, limit: int,
                      filters: dict) -> tuple[int, list[dict]]:
        """The post-plan decode/materialize pass of one pushdown query —
        the body :meth:`query` always had, factored so query_batch can
        run it per request behind a single shared planning pass. Must
        stay byte-identical to the retained :meth:`query_unpruned`
        oracle. ``limit`` <= 0 is a count-only page: (total, []) —
        matches the oracle's limit=0 behavior (Engine clamps to >= 1,
        but the distributed path forwards the caller's limit
        verbatim)."""
        from sitewhere_tpu.ops.query import host_filter_mask

        device = filters.get("device")
        etype = filters.get("etype")
        tenant = filters.get("tenant")
        assignment = filters.get("assignment")
        aux0 = filters.get("aux0")
        aux1 = filters.get("aux1")
        area = filters.get("area")
        customer = filters.get("customer")
        since_ms = filters.get("since_ms")
        until_ms = filters.get("until_ms")
        pred_cols = ["valid", "ts_ms"]
        for col, v in (("device", device), ("etype", etype),
                       ("tenant", tenant), ("assignment", assignment),
                       ("area", area), ("customer", customer)):
            if v is not None:
                pred_cols.append(col)
        if aux0 is not None or aux1 is not None:
            pred_cols.append("aux")
        total = 0
        # page candidates: (ts, scan_order, rank_in_segment, seg, row).
        # Sorting by (-ts, scan_order, rank) reproduces the reference
        # merge exactly: the full scan appends per-segment newest-first
        # pages in (part, start) order and stable-sorts on -ts, so ties
        # resolve by scan order then in-segment rank.
        kept: list[tuple[int, int, int, _Segment, int]] = []
        kth: int | None = None
        decoded: set[str] = set()
        for order_i, seg, full_match, ts_hi, cap_covers in plan_rows:
            # the page is CLOSED to this segment when it already holds
            # ``limit`` rows all strictly newer than anything the segment
            # can contain (strict: an equal-ts row could still win its
            # tie-break on scan order)
            page_closed = kth is not None and kth > ts_hi
            if page_closed and full_match:
                # zone maps prove every valid row matches and the cap
                # covers the segment: count it without touching the file
                total += seg.stats["rows"]
                self.count_shortcuts += 1
                continue
            need = ("valid", "ts_ms") if full_match else tuple(pred_cols)
            cols = self._cols_or_drop(seg, need)
            if cols is None:
                continue   # quarantined mid-query: rows unavailable
            decoded.add(seg.path)
            m = cols["valid"].astype(bool)
            if max_pos is not None and not cap_covers:
                cap = min(seg.count, max_pos.get(seg.part, 0) - seg.start)
                m[cap:] = False
            if not full_match:
                m &= host_filter_mask(
                    cols, device=device, etype=etype, tenant=tenant,
                    assignment=assignment, aux0=aux0, aux1=aux1,
                    area=area, customer=customer, since_ms=since_ms,
                    until_ms=until_ms)
            idx = np.nonzero(m)[0]
            total += int(idx.size)
            if page_closed or not idx.size:
                continue
            ts = cols["ts_ms"]
            sel = idx[np.argsort(-ts[idx], kind="stable")][:limit]
            kept.extend((int(ts[i]), order_i, j, seg, int(i))
                        for j, i in enumerate(sel))
            kept.sort(key=lambda t: (-t[0], t[1], t[2]))
            del kept[limit:]
            kth = kept[-1][0] if kept and len(kept) == limit else None
        self.plan_decoded += len(decoded)
        rows: list[dict] = []
        for ts_v, order_i, j, seg, i in kept:
            cols = self._cols_or_drop(seg, _COLUMNS)
            if cols is None:
                continue   # payload columns rotted behind good pred cols
            row = {c: cols[c][i] for c in _COLUMNS}
            row["part"] = seg.part
            row["pos"] = seg.start + i
            rows.append(row)
        return total, rows

    def query_unpruned(self, *, max_pos: dict[int, int] | None = None,
                       device: int | None = None, etype: int | None = None,
                       tenant: int | None = None, since_ms: int | None = None,
                       until_ms: int | None = None,
                       assignment: int | None = None,
                       aux0: int | None = None, aux1: int | None = None,
                       area: int | None = None, customer: int | None = None,
                       limit: int = 100,
                       device_parts: frozenset[int] | None = None,
                       assignment_parts: frozenset[int] | None = None,
                       ) -> tuple[int, list[dict]]:
        """The pre-pushdown full scan, kept VERBATIM as the parity oracle:
        decodes every eligible segment with its own ``np.load`` and
        filters row-by-row. :meth:`query` must return byte-identical
        (total, rows) — the smoke bench hard-gates it and the pushdown
        tests pin it across tie/bloom/gap edge cases."""
        total = 0
        top: list[tuple[int, dict]] = []
        for seg in self.segments:
            if max_pos is not None and seg.start >= max_pos.get(seg.part, 0):
                continue
            if since_ms is not None and seg.ts_max < since_ms:
                continue
            if until_ms is not None and seg.ts_min > until_ms:
                continue
            if device is not None and device_parts is not None \
                    and seg.part not in device_parts:
                continue
            with np.load(self.dir / seg.path) as z:
                m = np.asarray(z["valid"], bool).copy()
                cap = seg.count
                if max_pos is not None:
                    cap = min(cap, max_pos.get(seg.part, 0) - seg.start)
                    m[cap:] = False
                if device is not None:
                    m &= np.asarray(z["device"]) == device
                if etype is not None:
                    m &= np.asarray(z["etype"]) == etype
                if tenant is not None:
                    m &= np.asarray(z["tenant"]) == tenant
                if assignment is not None:
                    if assignment_parts is not None \
                            and seg.part not in assignment_parts:
                        m[:] = False
                    else:
                        m &= np.asarray(z["assignment"]) == assignment
                if aux0 is not None:
                    m &= np.asarray(z["aux"])[:, 0] == aux0
                if aux1 is not None:
                    m &= np.asarray(z["aux"])[:, 1] == aux1
                if area is not None:
                    m &= np.asarray(z["area"]) == area
                if customer is not None:
                    m &= np.asarray(z["customer"]) == customer
                ts = np.asarray(z["ts_ms"])
                if since_ms is not None:
                    m &= ts >= since_ms
                if until_ms is not None:
                    m &= ts <= until_ms
                idx = np.nonzero(m)[0]
                total += int(idx.size)
                if not idx.size:
                    continue
                # keep only this segment's newest ``limit`` matches
                order = idx[np.argsort(-ts[idx], kind="stable")][:limit]
                cols = {c: np.asarray(z[c])[order] for c in _COLUMNS}
                for j, i in enumerate(order):
                    row = {c: cols[c][j] for c in _COLUMNS}
                    row["part"] = seg.part
                    row["pos"] = seg.start + int(i)
                    top.append((int(ts[i]), row))
        top.sort(key=lambda t: -t[0])
        return total, [r for _, r in top[:limit]]
