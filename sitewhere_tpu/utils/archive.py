"""Long-term event retention: host-side spill of HBM ring segments to disk.

The reference retains FULL event history in an external time-series store
(InfluxDB/Cassandra/Warp10) and serves arbitrary date-range queries
(service-event-management/.../influxdb/InfluxDbDeviceEventManagement.java:63-161);
the HBM ring (core/store.py) is a fixed-capacity recency window. This module
is the retention tier between them: before a ring row can be overwritten,
its segment is spilled to an on-disk columnar file, and the engines'
``query_events`` transparently merges ring + archive so date ranges older
than the ring come back exactly like the reference's unbounded history.

Design (TPU-first):
- Spooling reads the ring with the SAME ``read_range`` program every time
  (fixed ``segment_rows`` chunk -> one compiled executable, no recompiles)
  and only at flush boundaries, never per event.
- A partition is one (shard, arena) sub-ring: spill order within a
  partition is the ring's write order, so a partition's segments tile
  absolute positions [0, spilled) contiguously.
- Segment files are columnar ``.npz`` (structure-of-arrays, like the ring
  itself); queries prune whole segments by their [ts_min, ts_max] interval
  before touching rows — the archive analog of time-series index pruning.
- Crash safety: segments are written to a temp name and renamed; the
  manifest is rebuilt from the segment files when missing or stale.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib

import numpy as np

_COLUMNS = ("etype", "device", "assignment", "tenant", "area", "customer",
            "asset", "ts_ms", "received_ms", "values", "vmask", "aux",
            "valid")


def mesh_topology(n_shards: int, arenas: int) -> str:
    """Canonical topology stamp of a mesh engine's archive — ONE producer
    for the stamp the engine writes, recovery matches, and migration
    rewrites."""
    return f"mesh/{n_shards}x{arenas}"


def single_topology(arenas: int) -> str:
    """Canonical topology stamp of a single-chip engine's archive."""
    return f"single/{arenas}"


@dataclasses.dataclass
class _Segment:
    part: int        # partition = shard * arenas + arena (0 for 1-ring)
    start: int       # absolute position of first row within the partition
    count: int
    ts_min: int
    ts_max: int
    path: str

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class EventArchive:
    """Directory of spilled ring segments + a queryable index.

    A partition is one independent sub-ring feeding this archive (an
    arena for a single-chip engine; (shard, arena) flattened for the
    mesh); each keeps its own spill watermark. ``topology`` labels the
    exact engine shape writing the archive (see __init__)."""

    def __init__(self, directory: str | pathlib.Path, segment_rows: int = 4096,
                 max_rows_per_part: int | None = None,
                 topology: str | None = None,
                 max_age_ms: int | None = None):
        self.dir = pathlib.Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.segment_rows = int(segment_rows)
        # partition-topology stamp: segment `part` indices are only
        # meaningful for the exact engine layout that wrote them — after an
        # elastic reshard (or a single<->mesh migration with equal
        # partition COUNTS) the same integers would resolve to the WRONG
        # shard/arena and shard-local device ids shift, so the stamp is a
        # full shape label (e.g. "mesh/8x1"), and any mismatch retires the
        # old data instead of misreading it
        self.topology = topology
        # retention policy (reference: per-assignment
        # INFLUX_RETENTION_POLICY override, InfluxDbDeviceEventManagement):
        # None = unbounded history; otherwise each partition keeps at most
        # this many archived rows and the OLDEST whole segments expire.
        # The newest archived rows duplicate the ring window (spill is
        # eager), so the queryable history beyond the ring is roughly
        # max_rows_per_part - arena_capacity: size the cap ABOVE the ring
        self.max_rows_per_part = max_rows_per_part
        # time-based retention (the closer Influx analog): a segment whose
        # NEWEST event is older than the partition's newest event minus
        # max_age_ms expires wholesale. Event-time based (ts_ms domain),
        # so replayed/backfilled history ages consistently
        self.max_age_ms = max_age_ms
        self.expired_rows = 0
        self.segments: list[_Segment] = []
        self.lost_rows = 0   # rows overwritten before they could spill
        # per-partition segments sorted by start (bisect lookups) + a
        # one-segment row cache: replay reads a segment in max_batch
        # chunks and must not re-extract the npz per chunk
        self._by_part: dict[int, list[_Segment]] = {}
        self._row_cache: tuple[str, dict] | None = None
        # monotone spill watermark per partition, independent of segment
        # PRESENCE: retention may expire the tail segment (backfilled event
        # times), and a watermark derived from surviving segments would
        # regress below the ring head — making the spooler re-spill and
        # re-expire the same rows forever
        self._spilled: dict[int, int] = {}
        # registered gaps: position ranges that NEVER held data (topology
        # migration pads history up to an arena boundary) — replay must
        # not count them as lost rows
        self._gaps: dict[int, list[list[int]]] = {}
        self._load_index()

    # ------------------------------------------------------------- index
    def _manifest_path(self) -> pathlib.Path:
        return self.dir / "index.json"

    def _load_index(self) -> None:
        # a crash mid-write leaves a *.npz.tmp — never adopted (the glob
        # below requires the final .npz name), just swept away here
        for stray in self.dir.glob("*.npz.tmp"):
            stray.unlink()
        manifest = self._manifest_path()
        known: dict[str, _Segment] = {}
        if manifest.exists():
            m = json.loads(manifest.read_text())
            stamped = m.get("topology", m.get("parts"))
            if (self.topology is not None and stamped is not None
                    and str(stamped) != self.topology):
                self._retire(str(stamped))
            else:
                for e in m.get("segments", []):
                    known[e["path"]] = _Segment(**e)
                self._spilled = {int(k): int(v)
                                 for k, v in m.get("spilled", {}).items()}
                self._gaps = {int(k): [[int(lo), int(hi)] for lo, hi in v]
                              for k, v in m.get("gaps", {}).items()}
        # adopt any segment file the manifest missed (crash between the
        # segment rename and the manifest rewrite) — but NEVER a file whose
        # own topology stamp disagrees (a manifest-less dir must not smuggle
        # old-topology partition indices past the retire check)
        for f in sorted(self.dir.glob("seg-*.npz")):
            if f.name in known:
                self.segments.append(known[f.name])
                continue
            with np.load(f) as z:
                # an archive opened with topology=None stamps np.str_("");
                # treat that like a missing stamp (same semantics as a
                # null manifest stamp) so such segments are adopted, not
                # retired, by a later topology-aware open
                seg_topo = (str(z["topology"]) if "topology" in z.files
                            else "") or None
                if (self.topology is not None and seg_topo is not None
                        and seg_topo != self.topology):
                    pass  # retired below, outside the np.load handle
                else:
                    seg_topo = None
                    ts = z["ts_ms"]
                    self.segments.append(_Segment(
                        part=int(z["part"]), start=int(z["start"]),
                        count=int(ts.shape[0]),
                        ts_min=int(ts.min()) if ts.size else 0,
                        ts_max=int(ts.max()) if ts.size else 0,
                        path=f.name))
            if seg_topo is not None:
                self._retire(seg_topo, files=[f])
        self.segments.sort(key=lambda s: (s.part, s.start))
        self._drop_covered()
        self._reindex()

    def _drop_covered(self) -> None:
        """Delete segment files whose row range is fully covered by a
        larger segment of the same partition — the leftovers of a
        compaction that crashed between the merged-segment rename and the
        source deletes (merged files exactly cover their sources, so
        covered == superseded)."""
        keep: list[_Segment] = []
        end: dict[int, int] = {}
        for s in sorted(self.segments,
                        key=lambda s: (s.part, s.start, -s.count)):
            if s.start + s.count <= end.get(s.part, 0):
                (self.dir / s.path).unlink(missing_ok=True)
                continue
            end[s.part] = max(end.get(s.part, 0), s.start + s.count)
            keep.append(s)
        self.segments = keep

    def _reindex(self) -> None:
        self._by_part = {}
        for s in self.segments:
            self._by_part.setdefault(s.part, []).append(s)
        for segs in self._by_part.values():
            segs.sort(key=lambda s: s.start)

    def _retire(self, old_topology: str,
                files: "list[pathlib.Path] | None" = None) -> None:
        """Move different-topology archive files aside (never delete
        history: the operator may migrate it offline). Runs before any
        index adoption, so the live archive never carries them."""
        import logging

        tag = old_topology.replace("/", "-")
        retired = self.dir / f"retired-{tag}"
        n = 0
        while retired.exists():
            n += 1
            retired = self.dir / f"retired-{tag}-{n}"
        retired.mkdir()
        if files is None:
            files = list(self.dir.glob("seg-*.npz")) + [self._manifest_path()]
        for f in files:
            if f.exists():
                f.rename(retired / f.name)
        logging.getLogger(__name__).warning(
            "archive topology changed (%s -> %s): previous history moved "
            "to %s; spill starts fresh",
            old_topology, self.topology, retired)

    def _save_index(self) -> None:
        tmp = self._manifest_path().with_suffix(".tmp")
        tmp.write_text(json.dumps(
            {"topology": self.topology,
             "spilled": self._spilled,
             "gaps": self._gaps,
             "segments": [s.to_json() for s in self.segments]}))
        tmp.replace(self._manifest_path())

    def spilled(self, part: int) -> int:
        """Next absolute position of ``part`` the spooler should write —
        monotone even after retention expires the newest-position
        segment."""
        ends = max((s.start + s.count for s in self._by_part.get(part, ())),
                   default=0)
        return max(self._spilled.get(part, 0), ends)

    def total_rows(self) -> int:
        return sum(s.count for s in self.segments)

    def register_gap(self, part: int, lo: int, hi: int) -> None:
        """Record [lo, hi) of ``part`` as positions that never held data
        (migration padding) — replay skips them without loss accounting."""
        if hi > lo:
            self._gaps.setdefault(part, []).append([int(lo), int(hi)])

    def gap_rows(self, part: int, lo: int, hi: int) -> int:
        """Rows of [lo, hi) covered by registered never-written gaps."""
        return sum(max(0, min(hi, g_hi) - max(lo, g_lo))
                   for g_lo, g_hi in self._gaps.get(part, ()))

    # ------------------------------------------------------------- write
    def append_segment(self, part: int, start: int, sl) -> None:
        """Persist one contiguous ring slice (a ``StoreSlice`` already on
        host). Idempotent: re-spooling an existing (part, start) range —
        e.g. after WAL replay — is a no-op."""
        name = f"seg-p{part:04d}-o{start:014d}-n{sl.ts_ms.shape[0]}.npz"
        path = self.dir / name
        end = start + int(sl.ts_ms.shape[0])
        self._spilled[part] = max(self._spilled.get(part, 0), end)
        if path.exists():
            return
        ts = np.asarray(sl.ts_ms)
        # temp name must NOT match the seg-*.npz recovery glob (write via a
        # file handle — np.savez would append .npz to a bare path)
        tmp = path.with_name(path.name + ".tmp")
        with open(tmp, "wb") as f:
            np.savez(f, part=np.int64(part), start=np.int64(start),
                     topology=np.str_(self.topology or ""),
                     **{c: np.asarray(getattr(sl, c)) for c in _COLUMNS})
        tmp.replace(path)
        self.segments.append(_Segment(
            part=part, start=start, count=int(ts.shape[0]),
            ts_min=int(ts.min()) if ts.size else 0,
            ts_max=int(ts.max()) if ts.size else 0, path=name))
        self.segments.sort(key=lambda s: (s.part, s.start))
        self._reindex()
        self._expire(part)
        self._save_index()

    def _expire(self, part: int) -> None:
        """Apply the retention policies: drop this partition's OLDEST whole
        segments while it exceeds ``max_rows_per_part``, and any segment
        whose newest event fell behind ``max_age_ms`` of the partition's
        newest. Expired rows are deliberate policy (counted separately
        from ``lost_rows``)."""
        if self.max_rows_per_part is None and self.max_age_ms is None:
            return
        segs = self._by_part.get(part, [])
        victims: list[_Segment] = []
        # phase 1 — row cap pops in WRITE order (oldest position first)
        if self.max_rows_per_part is not None:
            total = sum(s.count for s in segs)
            while segs and total > self.max_rows_per_part:
                victims.append(segs.pop(0))
                total -= victims[-1].count
        # phase 2 — age horizon from the SURVIVORS' newest event (a
        # just-popped segment must not inflate it), sweeping EVERY
        # segment: event time is client-supplied, so a backfilled segment
        # can sit behind a fresher one in write order
        if self.max_age_ms is not None and segs:
            horizon = max(s.ts_max for s in segs) - self.max_age_ms
            victims += [s for s in segs if s.ts_max < horizon]
            segs[:] = [s for s in segs if s.ts_max >= horizon]
        for victim in victims:
            self.expired_rows += victim.count
            self.segments.remove(victim)
            (self.dir / victim.path).unlink(missing_ok=True)
            if self._row_cache is not None \
                    and self._row_cache[0] == victim.path:
                self._row_cache = None
        if victims:
            self._reindex()

    # -------------------------------------------------------- maintenance
    def compact(self, target_rows: int | None = None) -> dict:
        """Merge runs of contiguous small segments per partition into
        files of up to ``target_rows`` (default 8x the spool segment) —
        the maintenance the reference delegates to its time-series
        store's own compaction (Influx shard compaction). Row positions
        are preserved, so by-id lookups, replay cursors, and the query
        cap are unaffected. Crash-safe: the merged file is renamed into
        place before the sources are deleted; a crash in between leaves
        covered sources that ``_load_index`` sweeps."""
        target = int(target_rows or 8 * self.segment_rows)
        merged_segments = files_removed = 0
        for part, segs in list(self._by_part.items()):
            i = 0
            while i < len(segs):
                run = [segs[i]]
                total = segs[i].count
                j = i + 1
                while (j < len(segs)
                       and segs[j].start == run[-1].start + run[-1].count
                       and total + segs[j].count <= target):
                    total += segs[j].count
                    run.append(segs[j])
                    j += 1
                if len(run) < 2:
                    i = j
                    continue
                cols: dict[str, list] = {c: [] for c in _COLUMNS}
                for s in run:
                    sc = self._segment_cols(s)
                    for c in _COLUMNS:
                        cols[c].append(sc[c])
                merged = {c: np.concatenate(cols[c]) for c in _COLUMNS}
                start = run[0].start
                name = f"seg-p{part:04d}-o{start:014d}-n{total}.npz"
                tmp = self.dir / (name + ".tmp")
                with open(tmp, "wb") as f:
                    np.savez(f, part=np.int64(part), start=np.int64(start),
                             topology=np.str_(self.topology or ""), **merged)
                tmp.replace(self.dir / name)
                ts = merged["ts_ms"]
                new_seg = _Segment(
                    part=part, start=start, count=total,
                    ts_min=int(ts.min()) if ts.size else 0,
                    ts_max=int(ts.max()) if ts.size else 0, path=name)
                for s in run:
                    (self.dir / s.path).unlink(missing_ok=True)
                    self.segments.remove(s)
                    files_removed += 1
                self.segments.append(new_seg)
                self._row_cache = None
                merged_segments += 1
                segs[i:j] = [new_seg]
                i += 1
        if merged_segments:
            self.segments.sort(key=lambda s: (s.part, s.start))
            self._reindex()
            self._save_index()
        return {"merged_segments": merged_segments,
                "files_removed": files_removed,
                "files_now": len(self.segments)}

    def disk_usage(self) -> dict:
        """Bytes on disk: live segments + everything under retired-*/
        (the disk-bounding observability knob). Tolerates concurrent
        expiry/compaction unlinking files mid-walk."""
        live = 0
        segments = list(self.segments)
        for s in segments:
            try:
                live += (self.dir / s.path).stat().st_size
            except FileNotFoundError:
                pass
            except OSError:
                pass
        retired = retired_files = 0
        for d in self.dir.glob("retired-*"):
            for f in d.rglob("*"):
                try:
                    if f.is_file():
                        retired += f.stat().st_size
                        retired_files += 1
                except OSError:
                    pass
        return {"live_bytes": live, "live_segments": len(segments),
                "retired_bytes": retired, "retired_files": retired_files}

    def purge_retired(self) -> int:
        """Delete every retired-*/ directory (call AFTER their history has
        been migrated to the new topology — reshard_snapshot's archive
        migration — or is otherwise expendable). Returns bytes
        reclaimed."""
        import shutil

        freed = 0
        for d in self.dir.glob("retired-*"):
            for f in d.rglob("*"):
                if f.is_file():
                    freed += f.stat().st_size
            shutil.rmtree(d)
        return freed

    def note_lost(self, count: int) -> None:
        """Record rows that wrapped before spooling (mis-sized trigger —
        surfaced in metrics the way the feed reports ``lag_lost``)."""
        self.lost_rows += int(count)

    # ------------------------------------------------------------- query
    def get_row(self, part: int, pos: int) -> dict | None:
        """Fetch one archived row by (partition, absolute position) — the
        by-id lookup for events evicted from the ring. Returns the ring
        column layout as a dict, or None if the position was never
        spilled."""
        seg = self._segment_for(part, pos)
        if seg is None:
            return None
        cols = self._segment_cols(seg)
        i = pos - seg.start
        if not bool(cols["valid"][i]):
            return None
        return {c: cols[c][i] for c in _COLUMNS}

    def _segment_for(self, part: int, pos: int) -> "_Segment | None":
        import bisect

        segs = self._by_part.get(part)
        if not segs:
            return None
        i = bisect.bisect_right(segs, pos, key=lambda s: s.start) - 1
        if i >= 0 and segs[i].start <= pos < segs[i].start + segs[i].count:
            return segs[i]
        return None

    def next_start(self, part: int, pos: int) -> int | None:
        """First archived position strictly after ``pos`` that is on disk
        — where replay resumes after a recorded-loss gap."""
        import bisect

        segs = self._by_part.get(part)
        if not segs:
            return None
        i = bisect.bisect_right(segs, pos, key=lambda s: s.start)
        return segs[i].start if i < len(segs) else None

    def _segment_cols(self, seg: "_Segment") -> dict:
        if self._row_cache is not None and self._row_cache[0] == seg.path:
            return self._row_cache[1]
        with np.load(self.dir / seg.path) as z:
            cols = {c: np.asarray(z[c]) for c in _COLUMNS}
        self._row_cache = (seg.path, cols)
        return cols

    def read_rows(self, part: int, start: int, count: int):
        """Contiguous archived rows [start, start+n) of a partition as a
        StoreSlice-compatible column namespace (n <= count; one segment per
        call — callers loop). Returns (cols, n); n == 0 means the range is
        not on disk (never spilled, or a recorded-loss gap — see
        :meth:`next_start`). Bisect lookup + one-segment cache, so chunked
        replay never rescans the index or re-extracts a segment file."""
        import types

        seg = self._segment_for(part, start)
        if seg is None:
            return None, 0
        i = start - seg.start
        n = min(count, seg.count - i)
        cols = self._segment_cols(seg)
        return types.SimpleNamespace(
            **{c: cols[c][i:i + n] for c in _COLUMNS}), n

    def query(self, *, max_pos: dict[int, int] | None = None,
              device: int | None = None, etype: int | None = None,
              tenant: int | None = None, since_ms: int | None = None,
              until_ms: int | None = None, assignment: int | None = None,
              aux0: int | None = None, aux1: int | None = None,
              area: int | None = None, customer: int | None = None,
              limit: int = 100,
              device_parts: frozenset[int] | None = None,
              assignment_parts: frozenset[int] | None = None,
              ) -> tuple[int, list[dict]]:
        """Newest-first filtered scan over archived rows.

        ``max_pos[part]`` caps the scan at rows already EVICTED from that
        partition's ring (absolute position < max_pos) so ring + archive
        results never overlap. ``device_parts``/``assignment_parts`` scope
        a shard-LOCAL id filter to the partitions of its owning shard (mesh
        engines — the id namespaces repeat per shard). Returns
        (total_matching, top rows) where each row is a plain dict of
        scalars/arrays in ring column layout plus ``part``/``pos``."""
        total = 0
        top: list[tuple[int, dict]] = []
        for seg in self.segments:
            if max_pos is not None and seg.start >= max_pos.get(seg.part, 0):
                continue
            if since_ms is not None and seg.ts_max < since_ms:
                continue
            if until_ms is not None and seg.ts_min > until_ms:
                continue
            if device is not None and device_parts is not None \
                    and seg.part not in device_parts:
                continue
            with np.load(self.dir / seg.path) as z:
                m = np.asarray(z["valid"], bool).copy()
                cap = seg.count
                if max_pos is not None:
                    cap = min(cap, max_pos.get(seg.part, 0) - seg.start)
                    m[cap:] = False
                if device is not None:
                    m &= np.asarray(z["device"]) == device
                if etype is not None:
                    m &= np.asarray(z["etype"]) == etype
                if tenant is not None:
                    m &= np.asarray(z["tenant"]) == tenant
                if assignment is not None:
                    if assignment_parts is not None \
                            and seg.part not in assignment_parts:
                        m[:] = False
                    else:
                        m &= np.asarray(z["assignment"]) == assignment
                if aux0 is not None:
                    m &= np.asarray(z["aux"])[:, 0] == aux0
                if aux1 is not None:
                    m &= np.asarray(z["aux"])[:, 1] == aux1
                if area is not None:
                    m &= np.asarray(z["area"]) == area
                if customer is not None:
                    m &= np.asarray(z["customer"]) == customer
                ts = np.asarray(z["ts_ms"])
                if since_ms is not None:
                    m &= ts >= since_ms
                if until_ms is not None:
                    m &= ts <= until_ms
                idx = np.nonzero(m)[0]
                total += int(idx.size)
                if not idx.size:
                    continue
                # keep only this segment's newest ``limit`` matches
                order = idx[np.argsort(-ts[idx], kind="stable")][:limit]
                cols = {c: np.asarray(z[c])[order] for c in _COLUMNS}
                for j, i in enumerate(order):
                    row = {c: cols[c][j] for c in _COLUMNS}
                    row["part"] = seg.part
                    row["pos"] = seg.start + int(i)
                    top.append((int(ts[i]), row))
        top.sort(key=lambda t: -t[0])
        return total, [r for _, r in top[:limit]]
