"""Flight recorder: fixed-size, lock-light ring of batch-lifecycle records.

The reference reconstructs a message's journey from Istio/Zipkin spans and
per-stage Prometheus histograms (SURVEY.md §5.1); the TPU-native engine's
batch path is a single process, so a hosted tracer would cost more than
the stages it measures. Instead every ingest batch gets ONE preallocated
record slot carrying monotonic timestamps for each lifecycle stage:

    ingest -> decode -> arena fill -> WAL append -> commit -> dispatch
           -> device-ready -> readback

``device_ready`` is harvested opportunistically: the arena-recycle wait
(ingest/arena.ArenaPool) already observes the step output before reusing
the staging buffers, so observing it costs ZERO extra host<->device
syncs; ``drain()`` backfills it for records whose arena was never
recycled before the readback. Records are dicts + a couple of lists —
marking a stage is one monotonic clock read and one dict store under the
GIL, no lock on the hot path (the ring lock covers only slot allocation
and index maintenance).

Trace ids are W3C-shaped (utils/tracing.py) and shared across ranks: a
forwarded sub-batch's owner-side record carries the SAME trace id as the
sender's, so `/api/instance/trace/<id>` resolves the full cross-rank
journey from any rank (parallel/cluster.get_trace fans out).
"""

from __future__ import annotations

import threading
import time

from sitewhere_tpu.utils.tracing import (current_traceparent, new_trace_id,
                                         trace_id_of)

# canonical stage ordering for rendering (records carry only the stages
# their path actually visited). ``wal_durable`` is the group-commit
# durability watermark: the moment the dispatch gate observed the
# batch's WAL records fsync'd.
STAGE_ORDER = ("decode", "arena_fill", "wal_append", "commit",
               "wal_durable", "dispatch", "device_ready", "readback")

# read-path lifecycle (kind="query" records): id resolution under the
# engine lock, the coalesced device program (including any wait to join a
# micro-batch), then host-side row formatting — all outside the lock
QUERY_STAGE_ORDER = ("lookup", "device", "format", "archive")


def query_stage_durations(stages_us: dict) -> dict:
    """Per-stage DURATIONS (ms) for one query record — the read-path
    sibling of :func:`stage_durations`, shared by bench.py's query
    breakdown so "device time" always means the same interval:

      lookup_ms   start -> lookup (mirror sync + string->id resolution,
                  the only part that holds the engine lock)
      device_ms   lookup -> device (coalesce wait + fused program +
                  result readback)
      format_ms   device -> format (host row formatting)

    Stages a record never visited yield None."""
    def delta(a, b):
        if a is None or b is None:
            return None
        return max(0.0, (b - a) / 1000.0)

    return {
        "lookup_ms": delta(0.0, stages_us.get("lookup")),
        "device_ms": delta(stages_us.get("lookup"),
                           stages_us.get("device")),
        "format_ms": delta(stages_us.get("device"),
                           stages_us.get("format")),
    }


def stage_durations(stages_us: dict) -> dict:
    """Per-stage DURATIONS (ms) from one record's cumulative ``stagesUs``
    offsets — the shared harvesting rule behind bench.py's per-stage
    breakdown and the stage-time autotuner, so both always agree on what
    "decode time" means:

      decode_ms        start -> decode mark (the native scan)
      wal_ms           decode/arena_fill -> wal_append (framing + buffer
                       or inline flush)
      dispatch_wait_ms commit -> dispatch (arena fill residency, the
                       durability gate, and any dispatch-depth wait)
      device_ms        dispatch -> device_ready (transfer + step)

    Stages a record never visited yield None."""
    def delta(a, b):
        if a is None or b is None:
            return None
        return max(0.0, (b - a) / 1000.0)

    decode = stages_us.get("decode")
    wal_from = stages_us.get("arena_fill", decode)
    return {
        "decode_ms": delta(0.0, decode),
        "wal_ms": delta(wal_from, stages_us.get("wal_append")),
        "dispatch_wait_ms": delta(stages_us.get("commit"),
                                  stages_us.get("dispatch")),
        "device_ms": delta(stages_us.get("dispatch"),
                           stages_us.get("device_ready")),
    }


class FlightRecord:
    """One batch's lifecycle. Stage marks are idempotent-overwrite (a
    multi-chunk ingest keeps the LAST completion per stage); ``meta``
    carries counts and path annotations."""

    __slots__ = ("trace_id", "kind", "tenant", "rank", "n_payloads",
                 "t0_unix_ms", "t0_ns", "stages", "meta", "harvested")

    def __init__(self, trace_id: str | None, kind: str, tenant: str,
                 rank: int, n_payloads: int):
        self.trace_id = trace_id
        self.kind = kind
        self.tenant = tenant
        self.rank = rank
        self.n_payloads = n_payloads
        self.t0_unix_ms = int(time.time() * 1000)
        self.t0_ns = time.perf_counter_ns()
        self.stages: dict[str, int] = {}
        self.meta: dict[str, object] = {}
        # consumed-once marker for the scrape-time SLO harvest (never
        # serialized; a record stays readable via recent()/records_of)
        self.harvested = False

    def mark(self, stage: str) -> None:
        self.stages[stage] = time.perf_counter_ns()

    def add(self, key: str, value) -> None:
        self.meta[key] = value

    def add_counts(self, summary: dict) -> None:
        for k in ("decoded", "failed", "staged", "spilled", "persisted"):
            v = summary.get(k)
            if v:
                self.meta[k] = v

    def to_dict(self) -> dict:
        """JSON-able view: per-stage offsets in microseconds from record
        creation (monotonic), plus identity and counts. Snapshots the
        stage dict first (C-level copy, atomic under the GIL): a scrape
        may read a record the ingest thread is still marking."""
        stages = dict(self.stages)
        meta = dict(self.meta)
        return {"traceId": self.trace_id, "kind": self.kind,
                "tenant": self.tenant, "rank": self.rank,
                "payloads": self.n_payloads, "startedMs": self.t0_unix_ms,
                "stagesUs": {name: round((ns - self.t0_ns) / 1000.0, 1)
                             for name, ns in stages.items()},
                **meta}


class _NullRecord:
    """No-op record handed out while the recorder is disabled — the hot
    path stays branch-free (mark/add are called unconditionally)."""

    trace_id = None
    stages: dict = {}
    meta: dict = {}

    def mark(self, stage: str) -> None:
        pass

    def add(self, key: str, value) -> None:
        pass

    def add_counts(self, summary: dict) -> None:
        pass

    def to_dict(self) -> dict:
        return {}


NULL_RECORD = _NullRecord()


class FlightRecorder:
    """Fixed-capacity ring of FlightRecords with a trace-id index.

    ``begin`` allocates a slot (evicting the oldest) under a short lock;
    everything after that is lock-free record mutation. ``bind`` exposes
    the batch's record to nested layers (the WAL append lives three
    frames below the ingest entry point) via a thread-local.
    """

    def __init__(self, capacity: int = 1024, rank: int = 0,
                 enabled: bool = True):
        if capacity < 1:
            raise ValueError("flight recorder needs capacity >= 1")
        self.capacity = capacity
        self.rank = rank
        self.enabled = enabled
        self._ring: list[FlightRecord | None] = [None] * capacity
        self._head = 0
        self._by_id: dict[str, list[FlightRecord]] = {}
        self._lock = threading.Lock()
        self._local = threading.local()
        self.dropped = 0    # records evicted before ever being read

    # ------------------------------------------------------------ record
    def begin(self, kind: str, tenant: str = "default", n_payloads: int = 0,
              traceparent: str | None = None) -> FlightRecord:
        """Start a record. ``traceparent`` (or the bound context's) names
        the trace this batch belongs to — a forwarded batch's owner-side
        record JOINS the sender's trace instead of opening a new one."""
        if not self.enabled:
            return NULL_RECORD
        tid = trace_id_of(traceparent) or new_trace_id(self.rank)
        rec = FlightRecord(tid, kind, tenant, self.rank, n_payloads)
        with self._lock:
            old = self._ring[self._head]
            if old is not None:
                peers = self._by_id.get(old.trace_id)
                if peers is not None:
                    try:
                        peers.remove(old)
                    except ValueError:
                        pass
                    if not peers:
                        del self._by_id[old.trace_id]
                self.dropped += 1
            self._ring[self._head] = rec
            self._head = (self._head + 1) % self.capacity
            self._by_id.setdefault(tid, []).append(rec)
        return rec

    def bind(self, rec):
        """Context manager making ``rec`` this thread's current record."""
        recorder = self

        class _Bind:
            def __enter__(self):
                self.prev = getattr(recorder._local, "rec", None)
                recorder._local.rec = rec
                return rec

            def __exit__(self, *exc):
                recorder._local.rec = self.prev

        return _Bind()

    def current(self) -> FlightRecord | _NullRecord:
        rec = getattr(self._local, "rec", None)
        return rec if rec is not None else NULL_RECORD

    # ------------------------------------------------------------- query
    def records_of(self, trace_id: str) -> list[dict]:
        with self._lock:
            recs = list(self._by_id.get(trace_id, ()))
        return [r.to_dict() for r in recs]

    def recent(self, limit: int = 50, kind: str | None = None) -> list[dict]:
        """Newest-first records (bounded by ``limit``). ``kind`` filters
        ("ingest", "query", ...) while scanning the WHOLE ring for
        matches — a burst of query records must not dilute an ingest-
        stage consumer's window (the autotuner steers by these) down to
        nothing before the limit is reached."""
        out = []
        with self._lock:
            i = (self._head - 1) % self.capacity
            for _ in range(self.capacity):
                rec = self._ring[i]
                if rec is not None and (kind is None or rec.kind == kind):
                    out.append(rec)
                    if len(out) >= limit:
                        break
                i = (i - 1) % self.capacity
        return [r.to_dict() for r in out]

    def harvest_completed(self, kind: str = "ingest",
                          terminal: str = "device_ready") -> list:
        """Records of ``kind`` whose ``terminal`` stage has been marked
        and that were never harvested before — marked-and-returned
        atomically under the ring lock, so the scrape-time SLO exporter
        observes every completed lifecycle EXACTLY once regardless of
        which scrape surface (local, federated, RPC) gets there first.
        Returns the live FlightRecord objects (the caller reads stage
        nanos directly; to_dict would round them to microseconds).

        The ring is the retention window: a record evicted between two
        scrapes is lost to the histogram — the SLO plane SAMPLES at
        scrape cadence, it is not an exact event count."""
        out = []
        with self._lock:
            for rec in self._ring:
                if (rec is not None and rec.kind == kind
                        and not rec.harvested and terminal in rec.stages):
                    rec.harvested = True
                    out.append(rec)
        return out

    def __len__(self) -> int:
        with self._lock:
            return sum(1 for r in self._ring if r is not None)

    def dump_error(self, logger) -> None:
        """Emit the recent lifecycle records on a pipeline error — the
        post-mortem the operator would otherwise reconstruct from logs."""
        try:
            import json

            recs = self.recent(16)
            logger.error("pipeline error — last %d flight records: %s",
                         len(recs), json.dumps(recs, default=str))
        except Exception:       # the dump must never mask the real error
            logger.exception("flight recorder dump failed")
