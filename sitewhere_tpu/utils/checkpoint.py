"""Checkpoint / resume: durable snapshots of the engine.

The reference has no state checkpointing — durability is Kafka offsets +
external databases, and the 5s window store is lossy on restart
(DeviceStatePipeline.java:84-86 in-memory store; SURVEY.md §5.5). The TPU
build does better by design: one snapshot captures the ENTIRE engine —
registry tables, device-state store, event ring, allocation counters,
metrics — plus the host mirrors (interners, device metadata, epoch base).
Pairing a snapshot with the replayable ingest log (utils/ingestlog.py)
gives exact at-least-once resume: restore the snapshot, replay the log
tail past the snapshot's store cursor, and the idempotent state merge
converges to the pre-crash state.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import pathlib

import jax
import numpy as np

from sitewhere_tpu.core.events import EpochBase
from sitewhere_tpu.engine import AssignmentInfo, DeviceInfo, Engine
from sitewhere_tpu.ops.readback import absolute_cursor


def _flatten_state(state) -> dict[str, np.ndarray]:
    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    return {jax.tree_util.keystr(path): np.asarray(leaf) for path, leaf in flat}


def save_engine(engine: Engine, directory: str | pathlib.Path) -> dict:
    """Write a full snapshot; returns the manifest."""
    directory = pathlib.Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    with engine.lock:
        # staged batches AND async-flushed outputs must both land before the
        # snapshot, or the saved mirrors lag the saved device state
        engine._sync_mirrors()
        arrays = _flatten_state(engine.state)
        np.savez_compressed(directory / "state.npz", **arrays)
        host = {
            "epoch_base_unix_s": engine.epoch.base_unix_s,
            "next_device": engine._next_device,
            "next_assignment": engine._next_assignment,
            "store_cursor": absolute_cursor(engine.state.store),
            "tokens": [engine.tokens.token(i) for i in range(len(engine.tokens))],
            "tenants": [engine.tenants.token(i) for i in range(len(engine.tenants))],
            "device_types": [engine.device_types.token(i)
                             for i in range(len(engine.device_types))],
            "channel_names": [engine.channel_map.names.token(i)
                              for i in range(len(engine.channel_map.names))],
            "alert_types": [engine.alert_types.token(i)
                            for i in range(len(engine.alert_types))],
            "areas": [engine.areas.token(i) for i in range(len(engine.areas))],
            "customers": [engine.customers.token(i)
                          for i in range(len(engine.customers))],
            "assets": [engine.assets.token(i) for i in range(len(engine.assets))],
            "event_ids": [engine.event_ids.token(i)
                          for i in range(len(engine.event_ids))],
            "token_device": {str(k): v for k, v in engine.token_device.items()},
            "devices": {
                str(did): dataclasses.asdict(info)
                for did, info in engine.devices.items()
            },
            "assignments": {
                str(aid): dataclasses.asdict(info)
                for aid, info in engine.assignments.items()
            },
            "device_slots": {str(k): v for k, v in engine.device_slots.items()},
            "dead_letters": engine.dead_letters[-4096:],
            "config": dataclasses.asdict(engine.config),
        }
        (directory / "host.json").write_text(json.dumps(host))
        manifest = {
            "format": 1,
            "arrays": len(arrays),
            "devices": len(engine.devices),
            "store_cursor": host["store_cursor"],
        }
        (directory / "manifest.json").write_text(json.dumps(manifest))
        if engine.wal is not None:
            # everything logged so far is reflected at this cursor; replay
            # after recovery starts here and old segments become prunable
            engine.wal.append_watermark(host["store_cursor"])
            engine.wal.sync()
        return manifest


def restore_engine(directory: str | pathlib.Path) -> Engine:
    """Reconstruct an engine from a snapshot directory."""
    from sitewhere_tpu.engine import EngineConfig

    directory = pathlib.Path(directory)
    host = json.loads((directory / "host.json").read_text())
    config = EngineConfig(**host["config"])
    engine = Engine(config)
    engine.epoch = EpochBase(host["epoch_base_unix_s"])

    # device state arrays: rebuild the pytree with saved leaves. A
    # metrics counter the snapshot predates (e.g. tenant_counters, added
    # in PR 3) keeps its fresh zeros — counters start over rather than
    # refusing to restore pre-upgrade history
    data = np.load(directory / "state.npz")
    flat, treedef = jax.tree_util.tree_flatten_with_path(engine.state)
    leaves = []
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        if key.startswith(".metrics.") and key not in data.files:
            leaves.append(leaf)
            continue
        arr = data[key]
        leaves.append(jax.numpy.asarray(arr))
    engine.state = jax.tree_util.tree_unflatten(treedef, leaves)

    # host mirrors
    for tok in host["tokens"]:
        engine.tokens.intern(tok)
    for t in host["tenants"]:
        engine.tenants.intern(t)
    for t in host["device_types"]:
        engine.device_types.intern(t)
    for n in host["channel_names"]:
        engine.channel_map.names.intern(n)
    for a in host["alert_types"]:
        engine.alert_types.intern(a)
    for a in host.get("areas", []):
        engine.areas.intern(a)
    for c in host.get("customers", []):
        engine.customers.intern(c)
    for a in host.get("assets", []):
        engine.assets.intern(a)
    for e in host.get("event_ids", []):
        engine.event_ids.intern(e)
    engine.token_device = {int(k): v for k, v in host["token_device"].items()}
    engine.devices = {
        int(k): DeviceInfo(**v) for k, v in host["devices"].items()
    }
    engine.assignments = {
        int(k): AssignmentInfo(**v)
        for k, v in host.get("assignments", {}).items()
    }
    engine.assignment_tokens = {
        info.token: aid for aid, info in engine.assignments.items()
    }
    engine.device_slots = {
        int(k): list(v) for k, v in host.get("device_slots", {}).items()
    }
    engine._next_device = host["next_device"]
    engine._next_assignment = host["next_assignment"]
    engine.dead_letters = list(host["dead_letters"])
    # conservation ledger (ISSUE 14): the restored device counters carry
    # the pre-crash history this process never staged — rebase BEFORE
    # any WAL replay so the ledger balances over replayed rows only
    engine.ledger.rebase(engine)
    return engine


def replay_records(wal, ingest_json, ingest_binary,
                   after_cursor: int = -1, run_cap: int = 4096) -> int:
    """Group a WAL's records into per-(wire-format, tenant) runs and feed
    them through the given batch-ingest callables — the ONE place that
    parses the record framing written by IngestHostMixin._wal_append
    (tag byte + tenant + NUL + payload). Shared by engine crash recovery
    (replay_wal_into) and cluster rank-reshard (cluster.py
    replay_wal_through). Returns records replayed."""
    from sitewhere_tpu.engine import WAL_JSON

    count = 0
    run_key: tuple | None = None
    run: list[bytes] = []

    def flush_run():
        nonlocal run
        if not run:
            return
        tag, tenant = run_key
        if tag == WAL_JSON:
            ingest_json(run, tenant=tenant)
        else:
            ingest_binary(run, tenant=tenant)
        run = []

    for rec in wal.replay(after_cursor=after_cursor):
        tag = rec[:1]
        sep = rec.index(b"\x00", 1)
        key = (tag, rec[1:sep].decode())
        if key != run_key or len(run) >= run_cap:
            flush_run()
            run_key = key
        run.append(rec[sep + 1:])
        count += 1
    flush_run()
    return count


def replay_wal_into(engine, after_cursor: int,
                    wal_dir: str | pathlib.Path | None) -> None:
    """Shared WAL-replay mechanism for both engines (single-node and
    distributed — identical recovery semantics by construction): resolve
    the live vs an explicitly named (foreign, read-only) log, group
    records into per-(wire-format, tenant) runs, feed them through the
    ingest path that originally accepted them, and re-attach the live WAL.
    ``engine`` provides wal / ingest_json_batch / ingest_binary_batch /
    flush."""
    from sitewhere_tpu.engine import WAL_BINARY, WAL_JSON  # noqa: F401
    from sitewhere_tpu.utils.ingestlog import IngestLog

    # never re-log records while replaying them
    live_wal, engine.wal = engine.wal, None
    foreign = wal_dir is not None and (
        live_wal is None
        or pathlib.Path(wal_dir).resolve() != live_wal.dir.resolve()
    )
    if foreign:
        # an explicitly named WAL (e.g. a copy on a recovery host) wins
        # over the config-path log the restored engine opened — opened
        # READ-ONLY so the preserved copy stays byte-identical
        wal = IngestLog(wal_dir, readonly=True)
    else:
        wal = live_wal

    replay_records(wal, engine.ingest_json_batch, engine.ingest_binary_batch,
                   after_cursor=after_cursor)
    engine.flush()
    # future traffic logs to the engine's configured WAL, never the
    # read-only replay copy
    if foreign:
        wal.close()
    engine.wal = live_wal
    if live_wal is None:
        # recovered from a wal_dir copy but config.wal_dir is unset: the
        # engine would silently continue with durability OFF — make the
        # operator aware new ingest is no longer logged
        logging.getLogger(__name__).warning(
            "WAL replay finished but engine has no live WAL "
            "(config.wal_dir is None): new ingest will NOT be durable")


def recover_engine(snapshot_dir: str | pathlib.Path,
                   wal_dir: str | pathlib.Path | None = None) -> Engine:
    """Full crash recovery: restore the snapshot, then replay the WAL tail
    past its watermark — each record through the wire format that
    originally accepted it (engine.py WAL_JSON/WAL_BINARY tags). The
    result converges to the pre-crash state (at-least-once; the state
    merge is timestamp-idempotent)."""
    snapshot_dir = pathlib.Path(snapshot_dir)
    engine = restore_engine(snapshot_dir)
    manifest = json.loads((snapshot_dir / "manifest.json").read_text())
    if wal_dir is None and engine.config.wal_dir is None:
        return engine
    replay_wal_into(engine, manifest["store_cursor"], wal_dir)
    return engine
