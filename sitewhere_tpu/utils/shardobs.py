"""Shard heat & skew observability plane for the SPMD engine (ISSUE 18).

PRs 16-17 made the mesh-sharded ``SpmdEngine`` the real engine, but the
observability stack saw it as one opaque box: four aggregate gauges at
shard granularity and nothing measuring load skew — yet the fused
``shard_map`` step is bulk-synchronous, so one hot shard gates every
dispatch for all N chips. This module is the host-side half of the
plane:

  * :class:`ShardHeatTracker` — decayed-EWMA events/s per
    (shard, tenant bucket) and per placement slot, computed from
    cumulative counter DELTAS at harvest time (the device-side tenant
    counter grid is already materialized by the fused step; reading it
    is a plain ``device_get``, no new program, no extra dispatch), plus
    the per-dispatch imbalance index fed by the scatter path's existing
    per-shard row bincount. Sustained skew escalates through the same
    two-consecutive-audit confirmation discipline as the PR-13
    conservation auditor.
  * :func:`spmd_heat_payload` — THE document behind
    ``GET /api/instance/spmd/heat``, the ``Instance.spmdHeat`` RPC, the
    ``Cluster.spmdHeat`` fan-out, and the debug bundle's "spmd"
    section: per-shard flow counters, the heat maps, top-K hot slots,
    and the skew posture. Non-SPMD engines answer ``{"spmd": False}``.

Everything here stays OUT of ``engine.metrics()`` (dispatch-shape
equality) like every plane before it; the Prometheus series live in
utils/metrics ``spmd_metrics``/``export_spmd_metrics``.

Import hygiene: this module must import with jax blocked (pinned by
tests/test_import_hygiene.py) — numpy + stdlib only; the engine hands
in plain host arrays.
"""

from __future__ import annotations

import json
import logging
import time

import numpy as np

logger = logging.getLogger(__name__)

# EWMA half-life of the heat maps: a slot that goes quiet loses half its
# heat every HEAT_HALFLIFE_S seconds, so "hottest" means "hottest about
# now", not "hottest since boot"
HEAT_HALFLIFE_S = 10.0
# max/mean routed-rows imbalance that counts as a skew breach; the fused
# step is bulk-synchronous, so index k means the mesh runs at ~1/k of
# its balanced throughput while the breach lasts
SKEW_THRESHOLD = 4.0
TOP_K_SLOTS = 8


class ShardHeatTracker:
    """Host-side heat maps + skew posture for one SpmdEngine.

    All mutation sites hold the engine lock (harvest runs under it, the
    dispatch path already does), so no lock of its own; ``enabled``
    toggles the per-dispatch accounting (the bench overhead estimator
    flips it per batch, the conservation-ledger discipline).

    Determinism: the tracker never reads a clock — callers pass
    ``now_s`` (the engine's harvest seam defaults it to
    ``time.monotonic()``), so a seeded stream replayed with the same
    harvest times yields byte-identical heat maps (pinned by
    tests/test_shardobs.py)."""

    __slots__ = ("n_shards", "n_slots", "halflife_s", "skew_threshold",
                 "enabled", "heat_grid", "slot_heat", "skew_index",
                 "accept_skew", "dispatches", "harvests",
                 "sustained_total", "_skew_hwm", "_suspect", "_last_t",
                 "_last_events", "_last_slot_rows")

    def __init__(self, n_shards: int, n_slots: int,
                 halflife_s: float = HEAT_HALFLIFE_S,
                 skew_threshold: float = SKEW_THRESHOLD):
        self.n_shards = int(n_shards)
        self.n_slots = int(n_slots)
        self.halflife_s = float(halflife_s)
        self.skew_threshold = float(skew_threshold)
        self.enabled = True
        self.heat_grid: np.ndarray | None = None   # [S, T] eps EWMA
        self.slot_heat = np.zeros(self.n_slots)    # [n_slots] eps EWMA
        self.skew_index = 1.0        # last dispatch's max/mean routed rows
        self.accept_skew = 1.0       # last harvest's max/mean accepted delta
        self.dispatches = 0
        self.harvests = 0
        self.sustained_total = 0
        self._skew_hwm = 1.0
        self._suspect = False
        self._last_t: float | None = None
        self._last_events: np.ndarray | None = None
        self._last_slot_rows: np.ndarray | None = None

    # ------------------------------------------------------------ dispatch
    def note_dispatch(self, rows_per_shard) -> float:
        """Per-dispatch imbalance index from the scatter path's existing
        per-shard row counts: max/mean over ALL shard lanes — every chip
        waits for the fullest lane, so max/mean IS the stall factor a
        straggler imposes on the whole mesh."""
        rows = np.asarray(rows_per_shard, dtype=np.int64)
        total = int(rows.sum())
        skew = (float(rows.max()) * self.n_shards / total) if total else 1.0
        self.skew_index = skew
        if skew > self._skew_hwm:
            self._skew_hwm = skew
        self.dispatches += 1
        return skew

    # ------------------------------------------------------------- harvest
    def harvest(self, grid: np.ndarray, slot_rows: np.ndarray,
                now_s: float) -> None:
        """EWMA update from cumulative counter deltas. ``grid`` is the
        UNFOLDED device tenant-counter grid ``[S, T, lanes]`` (lanes in
        TENANT_COUNTER_LANES order); heat counts the rows the shard
        actually processed for the bucket — accepted + invalid, the two
        lanes that partition ``processed``. ``slot_rows`` is the host
        router's cumulative rows-routed-per-slot array. The first call
        primes the baselines and reports zero heat (a rate needs two
        samples)."""
        ev = (grid[..., 0] + grid[..., 3]).astype(np.int64)   # [S, T]
        slots = np.asarray(slot_rows, dtype=np.int64)
        self.harvests += 1
        if self._last_t is None or self._last_events is None:
            self.heat_grid = np.zeros(ev.shape)
            self._last_events = ev
            self._last_slot_rows = slots.copy()
            self._last_t = float(now_s)
            return
        dt = max(float(now_s) - self._last_t, 1e-9)
        alpha = 1.0 - 0.5 ** (dt / self.halflife_s)
        if self.heat_grid is None or self.heat_grid.shape != ev.shape:
            self.heat_grid = np.zeros(ev.shape)
            self._last_events = np.zeros(ev.shape, np.int64)
        d_ev = np.maximum(ev - self._last_events, 0)
        self.heat_grid = ((1.0 - alpha) * self.heat_grid
                          + alpha * (d_ev / dt))
        d_slot = np.maximum(slots - self._last_slot_rows, 0)
        self.slot_heat = ((1.0 - alpha) * self.slot_heat
                          + alpha * (d_slot / dt))
        acc = d_ev.sum(axis=1)                                 # [S]
        total = int(acc.sum())
        self.accept_skew = (float(acc.max()) * self.n_shards / total
                            if total else 1.0)
        self._last_events = ev
        self._last_slot_rows = slots.copy()
        self._last_t = float(now_s)

    # ------------------------------------------------------------- posture
    @property
    def skew_hwm(self) -> float:
        """Peek (no reset): worst dispatch imbalance since the last
        scrape took it."""
        return max(self._skew_hwm, self.skew_index)

    def take_skew_hwm(self, reset: bool = True) -> float:
        """Worst dispatch imbalance since the last take — RESET on
        scrape so each sample reads "worst case this scrape window"
        (the PR-11 arena-HWM discipline)."""
        hwm = max(self._skew_hwm, self.skew_index)
        if reset:
            self._skew_hwm = self.skew_index
        return hwm

    def top_slots(self, k: int = TOP_K_SLOTS) -> list[tuple[int, float]]:
        """The K hottest placement slots, hottest first (quiet slots
        omitted) — the heat input ``placement.propose_moves`` feeds to
        ``decide_balance`` instead of guessing from rank-level p99."""
        order = np.argsort(-self.slot_heat, kind="stable")[:k]
        return [(int(s), float(self.slot_heat[s])) for s in order
                if self.slot_heat[s] > 0.0]

    def audit_skew(self) -> bool:
        """One skew audit (scrape-cadence). A breach must survive TWO
        consecutive audits before it escalates — a single hot dispatch
        between audits is a suspect, not a verdict (the PR-13
        conservation-auditor confirmation rule). Escalation returns
        True, bumps ``sustained_total``, and emits one loud structured
        log line; the caller owns the counter export."""
        breach = self.skew_index >= self.skew_threshold
        confirmed = breach and self._suspect
        self._suspect = breach and not confirmed
        if confirmed:
            self.sustained_total += 1
            logger.warning(
                "SPMD SKEW SUSTAINED %s",
                json.dumps({"skewIndex": round(self.skew_index, 3),
                            "threshold": self.skew_threshold,
                            "acceptSkew": round(self.accept_skew, 3),
                            "dispatches": self.dispatches}))
        return confirmed

    def skew_posture(self) -> dict:
        return {"index": round(self.skew_index, 4),
                "acceptIndex": round(self.accept_skew, 4),
                "hwm": round(self.skew_hwm, 4),
                "threshold": self.skew_threshold,
                "dispatches": self.dispatches,
                "sustained": self.sustained_total,
                "suspect": self._suspect}


def _bucket_names(tenants) -> dict[int, str]:
    """bucket index -> tenant name, the format_tenant_counter_grid
    naming rule (buckets past the named-tenant range label bucketN)."""
    from sitewhere_tpu.pipeline import TENANT_COUNTER_BUCKETS

    return {tid % TENANT_COUNTER_BUCKETS: tenants.token(tid)
            for tid in range(min(len(tenants), TENANT_COUNTER_BUCKETS))}


def heat_map_doc(tracker: ShardHeatTracker, tenants) -> dict:
    """{shard: {tenant: eps}} from the tracker's heat grid (quiet cells
    omitted; bucket naming mirrors format_tenant_counter_grid)."""
    if tracker.heat_grid is None:
        return {}
    names = _bucket_names(tenants)
    out: dict[str, dict[str, float]] = {}
    hg = tracker.heat_grid
    for s, b in zip(*np.nonzero(hg > 0.0)):
        out.setdefault(str(int(s)), {})[
            names.get(int(b), f"bucket{int(b)}")] = round(
                float(hg[s, b]), 3)
    return out


def spmd_heat_payload(engine, now_s: float | None = None) -> dict:
    """THE document behind ``GET /api/instance/spmd/heat``, the
    ``Instance.spmdHeat`` RPC, the cluster fan-out, and the debug
    bundle's "spmd" section: per-shard flow counters, the
    (shard, tenant) heat map, top-K hot slots, and the skew posture.
    Duck-typed like every surface before it — an engine without a
    shard plane answers ``{"spmd": False}``."""
    eng = getattr(engine, "local", engine)
    flow = getattr(eng, "shard_flow", None)
    if not callable(flow):
        return {"spmd": False}
    doc: dict = {"spmd": True,
                 "rank": getattr(engine, "rank", 0),
                 "engine": getattr(eng, "metrics_label", "e?"),
                 "generatedMs": int(time.time() * 1000),
                 "flow": flow()}
    harvest = getattr(eng, "harvest_shard_heat", None)
    tracker = harvest(now_s) if callable(harvest) else None
    if tracker is not None:
        doc["heat"] = heat_map_doc(tracker, eng.tenants)
        doc["slots"] = {"topK": [{"slot": s, "eps": round(eps, 3)}
                                 for s, eps in tracker.top_slots()],
                        "nSlots": tracker.n_slots,
                        "halflifeS": tracker.halflife_s}
        doc["skew"] = tracker.skew_posture()
    return doc
