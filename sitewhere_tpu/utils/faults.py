"""Deterministic fault injection for partition/failover chaos testing.

The reference platform's resilience story is exercised by killing pods
and partitioning brokers; here the cluster is plain TCP between ranks,
so the chaos lever is a seam INSIDE the peer-call path: a process-global
``FaultInjector`` that every ``_SyncPeer.call`` consults before touching
the wire. Tests and the chaos harness install a plan; production runs
never pay more than one module-attribute read per call.

Faults are keyed by (src_rank, dst_rank, method) and are DETERMINISTIC:
a plan carries a seed, and probabilistic rules draw from one
``random.Random(seed)`` stream, so a failing chaos run replays exactly
with the same seed (the property BENCH/chaos logs record).

Supported rules:

  * ``kill(rank)`` — every call TO that rank raises ``ConnectionError``
    immediately (the network view of a SIGKILL'd process: connect
    refused, no timeout burned);
  * ``drop(src, dst, prob, method_prefix)`` — the call raises
    ``ConnectionError`` with probability ``prob`` (lossy partition);
  * ``delay(src, dst, delay_s, prob, method_prefix)`` — the call sleeps
    before proceeding (congested link / slow peer);
  * ``partition(a, b, prob, method_prefix)`` — SYMMETRIC drop between a
    rank pair: calls in EITHER direction raise ``ConnectionError`` (the
    network-partition view, vs ``drop``'s one-directional loss) — the
    handoff chaos matrix (ISSUE 15) partitions coordinator/source/
    target pairs with it;
  * ``delay_jitter(src, dst, base_s, jitter_s, prob, method_prefix)`` —
    sleeps ``base_s`` plus a seeded draw in ``[0, jitter_s)`` (jittery
    congested link). The jitter draws from the SAME seeded stream as
    the probabilistic rules, so a chaos run replays exactly.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time

_ANY = -1


@dataclasses.dataclass
class _Rule:
    kind: str          # "drop" | "delay" | "partition" | "delay_jitter"
    src: int = _ANY
    dst: int = _ANY
    prob: float = 1.0
    delay_s: float = 0.0
    jitter_s: float = 0.0
    method_prefix: str = ""

    def matches(self, src: int, dst: int, method: str) -> bool:
        if not method.startswith(self.method_prefix):
            return False
        if self.kind == "partition":
            # symmetric: the (a, b) pair matches either direction
            fwd = ((self.src == _ANY or self.src == src)
                   and (self.dst == _ANY or self.dst == dst))
            rev = ((self.src == _ANY or self.src == dst)
                   and (self.dst == _ANY or self.dst == src))
            return fwd or rev
        return ((self.src == _ANY or self.src == src)
                and (self.dst == _ANY or self.dst == dst))


class FaultPlan:
    """A seeded, ordered set of fault rules (first match wins)."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rules: list[_Rule] = []
        self.killed: set[int] = set()

    def kill(self, rank: int) -> "FaultPlan":
        self.killed.add(rank)
        return self

    def revive(self, rank: int) -> "FaultPlan":
        self.killed.discard(rank)
        return self

    def drop(self, src: int = _ANY, dst: int = _ANY, prob: float = 1.0,
             method_prefix: str = "") -> "FaultPlan":
        self.rules.append(_Rule("drop", src, dst, prob,
                                method_prefix=method_prefix))
        return self

    def delay(self, src: int = _ANY, dst: int = _ANY, delay_s: float = 0.05,
              prob: float = 1.0, method_prefix: str = "") -> "FaultPlan":
        self.rules.append(_Rule("delay", src, dst, prob, delay_s,
                                method_prefix=method_prefix))
        return self

    def partition(self, a: int, b: int, prob: float = 1.0,
                  method_prefix: str = "") -> "FaultPlan":
        """Symmetric drop between ranks ``a`` and ``b``: every call in
        either direction fails like a severed link (ISSUE 15 chaos
        matrix; ``drop`` stays one-directional)."""
        self.rules.append(_Rule("partition", a, b, prob,
                                method_prefix=method_prefix))
        return self

    def delay_jitter(self, src: int = _ANY, dst: int = _ANY,
                     base_s: float = 0.02, jitter_s: float = 0.05,
                     prob: float = 1.0,
                     method_prefix: str = "") -> "FaultPlan":
        """Seeded jittery delay: ``base_s`` plus a deterministic draw
        in ``[0, jitter_s)`` from the plan's RNG stream — same seed,
        same sleep sequence."""
        self.rules.append(_Rule("delay_jitter", src, dst, prob, base_s,
                                jitter_s, method_prefix=method_prefix))
        return self


class FaultInjector:
    """Evaluates a plan on the peer-call path. Thread-safe: the RNG draw
    is the only shared mutation and sits under a lock (call volume on
    the chaos paths is nowhere near lock-contention scale)."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._rng = random.Random(plan.seed)
        self._lock = threading.Lock()
        self.counters = {"dropped": 0, "delayed": 0, "killed_refused": 0,
                         "partitioned": 0, "jitter_delayed": 0}

    def _draw(self) -> float:
        with self._lock:
            return self._rng.random()

    def before_call(self, src: int, dst: int, method: str) -> None:
        """Raise/delay per the plan; called before the frame is sent."""
        if dst in self.plan.killed:
            self.counters["killed_refused"] += 1
            raise ConnectionError(
                f"fault injection: rank {dst} is killed (from rank {src})")
        for rule in self.plan.rules:
            if not rule.matches(src, dst, method):
                continue
            if rule.prob < 1.0 and self._draw() >= rule.prob:
                continue
            if rule.kind == "drop":
                self.counters["dropped"] += 1
                raise ConnectionError(
                    f"fault injection: dropped {method} "
                    f"rank {src}->{dst}")
            if rule.kind == "partition":
                self.counters["partitioned"] += 1
                raise ConnectionError(
                    f"fault injection: partition {rule.src}<->{rule.dst} "
                    f"severed {method} rank {src}->{dst}")
            if rule.kind == "delay":
                self.counters["delayed"] += 1
                time.sleep(rule.delay_s)
            if rule.kind == "delay_jitter":
                self.counters["jitter_delayed"] += 1
                time.sleep(rule.delay_s + self._draw() * rule.jitter_s)
            return   # first match wins


# process-global seam; None = zero-overhead fast path
_INJECTOR: FaultInjector | None = None


def install(plan: FaultPlan) -> FaultInjector:
    global _INJECTOR
    _INJECTOR = FaultInjector(plan)
    return _INJECTOR


def clear() -> None:
    global _INJECTOR
    _INJECTOR = None


def check(src: int, dst: int, method: str) -> None:
    """The one call sites make: no-op unless a plan is installed."""
    inj = _INJECTOR
    if inj is not None:
        inj.before_call(src, dst, method)
