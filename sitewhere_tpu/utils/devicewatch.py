"""Device-plane telemetry (ISSUE 11): the XLA side of the microscope.

PRs 3/7/10 instrumented the HOST (flight records, federated metrics,
span timelines, thread profiler); the XLA programs themselves stayed a
black box — nothing counted compiles or retraces, nothing accounted for
HBM occupancy, nothing exported per-program cost. This module adds the
three missing legs:

* **Compile/retrace watchdog** — :func:`watched_jit` wraps a jitted
  callable in a PASSTHROUGH shim (dispatch semantics untouched; jax's
  own jit cache keeps serving) that tracks the distinct abstract shape
  keys flowing through it. The first dispatch of a globally-new key is
  a compile: its wall time lands in ``swtpu_xla_compile_seconds`` and,
  for cost-enabled families, a lower-only pass captures
  ``cost_analysis`` flops/bytes (once per family by default — the pass
  re-traces, roughly doubling a compiling dispatch; set
  ``SWTPU_XLA_COST=all`` to re-capture on every compile. The AOT query
  path always captures exactly, from its own executable). Every :class:`WatchScope` declares an
  expected-distinct-shape budget (one program per bucket — e.g. one per
  ``(Q bucket, limit bucket)`` for the query path, one per scan_chunk
  program for ingest); a key beyond the budget increments the loud
  ``swtpu_xla_retrace_excess_total``, logs the offending shape diff,
  and in strict mode (``SWTPU_XLA_STRICT=1`` or
  :func:`strict_retraces`) raises :class:`RetraceError` BEFORE
  dispatching — a standing guard for the shape invariants PR 5/10 pin
  by hand.

* **Memory ledger** — :func:`memory_ledger` sizes the ring store,
  registry/state tables, staging-arena pool, archive segment cache and
  process-wide live jax arrays at scrape time (``nbytes`` walk;
  ``device.memory_stats()`` where the backend provides it — TPU yes,
  CPU returns None), exported as ``swtpu_device_mem_*`` gauges and
  served at ``GET /api/instance/device/memory``. High-watermarks
  (arena occupancy, staged backlog) reset on scrape so each sample
  reads "worst case this window".

* **Per-program cost & device time** — ``cost_analysis()`` captured
  once per compile and exported per family; device execution-time
  histograms harvested from the existing flight records at scrape time
  (the hot path pays nothing — see ``metrics.harvest_slo``); and
  :func:`capture_device_profile` wraps ``jax.profiler`` for the
  ``GET /api/instance/profile/device`` endpoint so hardware runs can
  pull real TPU timelines next to the PR-10 Perfetto export.

Nothing here touches ``engine.metrics()`` — the dispatch-shape equality
pin holds with the watchdog enabled, like every plane before it.
"""

from __future__ import annotations

import contextlib
import logging
import math
import os
import tempfile
import threading
import time
from typing import Any

import jax

from sitewhere_tpu.utils.metrics import REGISTRY, devicewatch_metrics

log = logging.getLogger(__name__)


class RetraceError(RuntimeError):
    """Strict-mode watchdog verdict: a program family compiled a shape
    beyond its declared budget (shape churn). Raised BEFORE the dispatch
    runs, so donated engine state is never consumed by the offending
    call."""


# --------------------------------------------------------------------------
# Abstract shape keys
# --------------------------------------------------------------------------

def _leaf_desc(leaf):
    """A cheap, stable descriptor for one call-tree leaf: the abstract
    value's (shape, dtype, weak_type) tuple — exactly what decides a jit
    retrace — for arrays and scalars, ``repr`` for static leaves jax
    would hash by value (meshes, configs). Tuples, not formatted
    strings: keys are computed on every watched dispatch, the readable
    form only when a budget violation needs a log line."""
    try:
        aval = jax.core.get_aval(leaf)
        return (tuple(aval.shape), aval.dtype.name,
                bool(getattr(aval, "weak_type", False)))
    except Exception:
        return repr(leaf)[:120]


def _fmt_desc(desc) -> str:
    """Human form of a :func:`_leaf_desc` descriptor for diff logging."""
    if isinstance(desc, tuple) and len(desc) == 3:
        shape, dtype, weak = desc
        dims = ",".join(str(d) for d in shape)
        return f"{dtype}[{dims}]" + ("~weak" if weak else "")
    return str(desc)


def abstract_key(args: tuple, kwargs: dict,
                 statics: tuple = ()) -> tuple | None:
    """The watchdog's shape key for one call: pytree structure hash +
    per-leaf abstract descriptors (+ static values by repr). Returns
    None when any leaf is a tracer — the call is being inlined into an
    enclosing jit trace and must pass through untouched."""
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    for leaf in leaves:
        if isinstance(leaf, jax.core.Tracer):
            return None
    return (hash(treedef), tuple(_leaf_desc(l) for l in leaves),
            tuple(repr(s) for s in statics))


def _key_diff(old: tuple, new: tuple) -> str:
    """First differing leaves between two shape keys — the "what churned"
    payload of the watchdog's log line."""
    olds, news = old[1], new[1]
    if old[0] != new[0]:
        return ("pytree STRUCTURE changed "
                f"({len(olds)} -> {len(news)} leaves)")
    diffs = [f"leaf[{i}]: {_fmt_desc(a)} -> {_fmt_desc(b)}"
             for i, (a, b) in enumerate(zip(olds, news)) if a != b]
    diffs += [f"static[{i}]: {a} -> {b}"
              for i, (a, b) in enumerate(zip(old[2], new[2])) if a != b]
    return "; ".join(diffs[:6]) + (" ..." if len(diffs) > 6 else "")


def _cost_dict(raw) -> dict | None:
    """Normalize a ``cost_analysis()`` result (dict on some jax builds,
    [dict] on others) to ``{"flops": f, "bytes_accessed": b}``."""
    if isinstance(raw, (list, tuple)):
        raw = raw[0] if raw else None
    if not isinstance(raw, dict):
        return None
    out = {}
    if "flops" in raw:
        out["flops"] = float(raw["flops"])
    if "bytes accessed" in raw:
        out["bytes_accessed"] = float(raw["bytes accessed"])
    return out or None


# --------------------------------------------------------------------------
# Watch core
# --------------------------------------------------------------------------

class _Family:
    """Process-global per-family aggregate: counters, last compile cost,
    the globally-compiled key set (so a second engine reusing jax's warm
    cache counts a HIT, not a compile), and the live scopes whose
    distinct keys sum into ``swtpu_xla_programs_live``."""

    __slots__ = ("name", "compiles", "hits", "excess", "last_cost",
                 "last_compile_s", "keys", "scopes")

    def __init__(self, name: str):
        self.name = name
        self.compiles = 0
        self.hits = 0
        self.excess = 0
        self.last_cost: dict | None = None
        self.last_compile_s: float | None = None
        self.keys: set = set()           # (fn_id, shape_key) ever compiled
        self.scopes: list = []           # weakrefs, pruned on snapshot


class WatchScope:
    """One watched seam's program book-keeping (per engine program, per
    QueryBatcher, or per module-level kernel): distinct shape keys seen,
    grouped into budget buckets with a per-bucket allowance. The scope —
    not the family — owns the budget, so two engines with different
    store shapes can never trip each other's watchdog."""

    def __init__(self, watch: "DeviceWatch", family: str,
                 allowance: int = 1):
        import weakref

        self.watch = watch
        self.family = family
        self.default_allowance = max(1, int(allowance))
        self._keys: dict[tuple, Any] = {}        # key -> bucket
        self._buckets: dict[Any, list] = {}      # bucket -> [keys]
        self._extra: dict[Any, int] = {}         # bucket -> extra allowance
        fam = watch._family(family)
        fam.scopes.append(weakref.ref(self))

    # ------------------------------------------------------------- budget
    def allow(self, n: int = 1, bucket: Any = "program") -> None:
        """Raise one bucket's allowance — the declaration hook a
        legitimate shape transition calls (``set_geofence_zones``
        recompiles every step family on purpose)."""
        self._extra[bucket] = self._extra.get(bucket, 0) + int(n)

    def _allowance(self, bucket) -> int:
        return self.default_allowance + self._extra.get(bucket, 0)

    @property
    def live_programs(self) -> int:
        return len(self._keys)

    # ------------------------------------------------------------ observe
    def observe(self, key: tuple, bucket: Any, fn_id: int = 0) -> str:
        """Classify one watched call: ``"seen"`` (scope already holds the
        key), ``"hit"`` (new to this scope, but some scope already
        compiled it — jax's cache is warm), or ``"compile"``. Applies the
        budget on scope-new keys; strict violations raise before the key
        registers (so the caller never dispatches)."""
        watch = self.watch
        fam = watch._family(self.family)
        with watch._lock:
            if key in self._keys:
                fam.hits += 1
                watch._inst["hits"].inc(family=self.family)
                return "seen"
            over = None
            if bucket is not None:
                held = self._buckets.setdefault(bucket, [])
                if len(held) >= self._allowance(bucket):
                    over = held[0]
            if over is not None:
                fam.excess += 1
                watch._inst["excess"].inc(family=self.family)
                diff = _key_diff(over, key)
                log.warning(
                    "devicewatch: retrace budget exceeded for family %r "
                    "bucket %r (%d program(s) allowed): %s",
                    self.family, bucket, self._allowance(bucket), diff)
                if watch.strict:
                    raise RetraceError(
                        f"family {self.family!r} bucket {bucket!r} "
                        f"exceeded its {self._allowance(bucket)}-program "
                        f"shape budget: {diff}")
            self._keys[key] = bucket
            if bucket is not None and over is None:
                # excess keys do NOT consume budget: a later allow()
                # re-arms the bucket, and every further distinct churn
                # shape warns again (a storm stays loud per shape)
                self._buckets[bucket].append(key)
            gkey = (fn_id, key)
            if gkey in fam.keys:
                fam.hits += 1
                watch._inst["hits"].inc(family=self.family)
                return "hit"
            fam.keys.add(gkey)
            return "compile"

    def note_compile(self, seconds: float, cost: dict | None) -> None:
        watch = self.watch
        fam = watch._family(self.family)
        with watch._lock:
            fam.compiles += 1
            fam.last_compile_s = seconds
            if cost is not None:
                fam.last_cost = cost
        watch._inst["compiles"].inc(family=self.family)
        watch._inst["compile"].observe(seconds, family=self.family)

    def record_aot(self, key: Any, bucket: Any, seconds: float,
                   compiled=None) -> None:
        """Record an explicit ``lower().compile()`` the caller already
        timed (the QueryBatcher's AOT path) — exact compile seconds and
        cost from the same executable."""
        cost = None
        if compiled is not None:
            try:
                cost = _cost_dict(compiled.cost_analysis())
            except Exception:
                cost = None
        # scope-unique key: every AOT compile is a REAL compile (the
        # caller just ran lower().compile()), so it must never dedup
        # against another engine's same-bucket program
        self.observe(("aot", id(self), key), bucket)
        self.note_compile(seconds, cost)


class WatchedProgram:
    """Passthrough wrapper around one jitted callable. Dispatch goes to
    the wrapped function verbatim (jax's jit cache unchanged); the shim
    only classifies each call's shape key and, on a genuine compile,
    times the dispatch and optionally captures a lower-only cost
    analysis. ``.lower`` and every other attribute pass through, so AOT
    users (the QueryBatcher) and introspection keep working."""

    __slots__ = ("fn", "scope", "bucket", "cost", "static_argnames",
                 "_sig")

    def __init__(self, fn, scope: WatchScope, bucket: Any = "program",
                 cost: bool = False, static_argnames: tuple = ()):
        self.fn = fn
        self.scope = scope
        self.bucket = bucket
        self.cost = cost
        self.static_argnames = tuple(static_argnames)
        self._sig = None
        if self.static_argnames:
            import inspect

            try:
                self._sig = inspect.signature(fn)
            except (TypeError, ValueError):
                self._sig = None

    def _statics(self, args, kwargs) -> tuple:
        """Static argument VALUES for the key (two ``limit`` values share
        one weak-int32 aval — only the value tells the programs apart)."""
        if self._sig is None:
            return ()
        try:
            bound = self._sig.bind(*args, **kwargs)
        except TypeError:
            return ()
        return tuple(bound.arguments.get(n) for n in self.static_argnames)

    def __call__(self, *args, **kwargs):
        watch = self.scope.watch
        if not watch.enabled:
            return self.fn(*args, **kwargs)
        statics = self._statics(args, kwargs)
        key = abstract_key(args, kwargs, statics)
        if key is None:      # tracer-staged: inlining into an outer jit
            return self.fn(*args, **kwargs)
        bucket = self.bucket
        if bucket is BY_STATICS:
            # one budget bucket per static-argument tuple: a family whose
            # static (e.g. ``limit``) legitimately takes several values
            # gets one program per value, not one program total
            bucket = ("statics",) + tuple(repr(s) for s in statics)
        verdict = self.scope.observe(key, bucket, fn_id=id(self.fn))
        if verdict != "compile":
            return self.fn(*args, **kwargs)
        cost = None
        if self.cost and (watch.cost_all or watch._family(
                self.scope.family).last_cost is None):
            try:
                cost = _cost_dict(
                    self.fn.lower(*args, **kwargs).cost_analysis())
            except Exception:
                cost = None
        t0 = time.perf_counter()
        out = self.fn(*args, **kwargs)
        self.scope.note_compile(time.perf_counter() - t0, cost)
        return out

    def lower(self, *args, **kwargs):
        return self.fn.lower(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self.fn, name)


class DeviceWatch:
    """Process-global watchdog state (one XLA compile cache per process,
    one watch). ``enabled`` gates ALL per-dispatch work — the bench
    toggles it per batch for the overhead gate; ``strict`` turns budget
    violations into :class:`RetraceError` (tests, CI)."""

    def __init__(self):
        self.enabled = True
        self.strict = os.environ.get("SWTPU_XLA_STRICT") == "1"
        # cost capture for jit-watched families needs a lower-only pass
        # (re-trace, no backend compile) — roughly doubling a compiling
        # dispatch. Default: once per family (the AOT query path always
        # captures exactly, from its own executable); SWTPU_XLA_COST=all
        # re-captures on every compile.
        self.cost_all = os.environ.get("SWTPU_XLA_COST") == "all"
        self._lock = threading.RLock()
        self._families: dict[str, _Family] = {}
        self._inst = devicewatch_metrics(REGISTRY)

    def _family(self, name: str) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(name)
            return fam

    def scope(self, family: str, allowance: int = 1) -> WatchScope:
        return WatchScope(self, family, allowance)

    # ----------------------------------------------------------- posture
    def compile_totals(self) -> dict[str, int]:
        """family -> programs compiled so far (the loadgen/bench delta
        source for "recompiles during this run")."""
        with self._lock:
            return {n: f.compiles for n, f in self._families.items()}

    def excess_total(self) -> int:
        with self._lock:
            return sum(f.excess for f in self._families.values())

    def posture(self) -> dict:
        """Per-family compile posture for the debug bundle and the
        ``/api/instance/device/memory`` breakdown."""
        out = {}
        with self._lock:
            for name, fam in self._families.items():
                live = 0
                alive = []
                for ref in fam.scopes:
                    sc = ref()
                    if sc is not None:
                        alive.append(ref)
                        live += sc.live_programs
                fam.scopes[:] = alive
                out[name] = {
                    "programsLive": live,
                    "compiles": fam.compiles,
                    "cacheHits": fam.hits,
                    "retraceExcess": fam.excess,
                    "lastCompileS": fam.last_compile_s,
                    "lastCost": fam.last_cost,
                }
        return out


WATCH = DeviceWatch()

# Bucket sentinel: derive the budget bucket per call from the watched
# program's STATIC argument values (see WatchedProgram.__call__).
BY_STATICS = object()


def watched_jit(fn, family: str, static_argnames: tuple = (),
                bucket: Any = None, cost: bool = False,
                allowance: int = 1) -> WatchedProgram:
    """Wrap an already-jitted module-level kernel in a process-global
    watch scope. ``bucket=None`` leaves the family unbudgeted (metrics
    only) — module kernels legitimately serve many shapes across
    engines; per-engine seams get budgets via :class:`EngineWatch`."""
    return WatchedProgram(fn, WATCH.scope(family, allowance),
                          bucket=bucket, cost=cost,
                          static_argnames=static_argnames)


def compile_totals() -> dict[str, int]:
    return WATCH.compile_totals()


def compile_posture() -> dict:
    return WATCH.posture()


@contextlib.contextmanager
def strict_retraces():
    """Strict mode for the enclosed block: budget violations raise
    :class:`RetraceError` instead of counting — the test-suite form of
    ``SWTPU_XLA_STRICT=1``."""
    prev = WATCH.strict
    WATCH.strict = True
    try:
        yield WATCH
    finally:
        WATCH.strict = prev


class EngineWatch:
    """Per-engine watchdog handle: one fresh :class:`WatchScope` per
    wrapped program (so a scan-chunk rebuild starts a clean budget) plus
    the AOT scope the QueryBatcher records into. ``enabled=False``
    (EngineConfig.devicewatch) returns callables unwrapped — zero
    dispatch-path change."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._wrapped: dict[str, WatchedProgram] = {}
        self._aot: dict[str, WatchScope] = {}

    def wrap(self, fn, family: str, cost: bool = False,
             static_argnames: tuple = (), bucket: Any = "program"):
        if not self.enabled:
            return fn
        w = WatchedProgram(fn, WATCH.scope(family), bucket=bucket,
                           cost=cost, static_argnames=static_argnames)
        self._wrapped[family] = w
        return w

    def allow(self, n: int = 1) -> None:
        """Grant every wrapped program +n shapes — called by seams that
        legitimately change the state's abstract shape (geofence zone
        installs swap a pytree leaf in/out)."""
        for w in self._wrapped.values():
            w.scope.allow(n)

    def record_aot(self, family: str, key: Any, bucket: Any,
                   seconds: float, compiled=None) -> None:
        if not self.enabled:
            return
        scope = self._aot.get(family)
        if scope is None:
            scope = self._aot[family] = WATCH.scope(family)
        scope.record_aot(key, bucket, seconds, compiled)


# --------------------------------------------------------------------------
# Memory ledger
# --------------------------------------------------------------------------

def _tree_nbytes(tree) -> int:
    """Byte size of a pytree's array leaves from shape/dtype metadata —
    safe on DONATED (deleted) jax arrays, whose data is gone but whose
    aval survives."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += int(math.prod(shape)) * dtype.itemsize
    return total


def live_array_stats() -> dict | None:
    """Process-wide live jax buffers (count + bytes) — the closest CPU
    analog of ``device.memory_stats()``; on TPU both are exported and
    should roughly reconcile."""
    try:
        arrs = jax.live_arrays()
        total = 0
        for a in arrs:
            try:
                total += int(a.nbytes)
            except Exception:
                continue          # deleted between listing and sizing
        return {"count": len(arrs), "bytes": total}
    except Exception:
        return None


def backend_memory_stats() -> dict | None:
    """Per-device allocator stats where the backend provides them (TPU:
    bytes_in_use / peak_bytes_in_use / largest_free_block; CPU: None)."""
    out = {}
    for d in jax.devices():
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if ms:
            out[str(d)] = {k: int(v) for k, v in ms.items()
                           if isinstance(v, (int, float))}
    return out or None


def memory_ledger(engine, reset_hwm: bool = False) -> dict:
    """Scrape-time accounting of everything this engine keeps resident:
    device-side state tables (computed from avals, so donation can't
    break the walk), host staging arenas, the archive's decoded-segment
    cache, and process-wide live arrays. ``reset_hwm`` drains the
    high-watermarks (the SCRAPE semantics — "worst case since the last
    scrape"); peeks (REST endpoint, debug bundle) leave them intact."""
    eng = getattr(engine, "local", engine)   # cluster facade -> rank local
    comp: dict[str, int] = {}
    st = getattr(eng, "state", None)
    if st is not None:
        comp["ring_store"] = _tree_nbytes(st.store)
        comp["registry"] = _tree_nbytes(st.registry)
        comp["device_state"] = _tree_nbytes(st.device_state)
        comp["pipeline_metrics"] = _tree_nbytes(st.metrics)
        if getattr(st, "windows", None) is not None:
            comp["telemetry_windows"] = _tree_nbytes(st.windows)
        if getattr(st, "zones", None) is not None:
            comp["geofence_zones"] = _tree_nbytes(st.zones)
    pool = getattr(eng, "_arena_pool", None)
    if pool is not None and hasattr(pool, "nbytes"):
        comp["arena_pool"] = int(pool.nbytes)
    arch = getattr(eng, "archive", None)
    cache = getattr(arch, "cache", None) if arch is not None else None
    if cache is not None and hasattr(cache, "nbytes"):
        comp["segment_cache"] = int(cache.nbytes)
    hwm: dict[str, int] = {}
    if pool is not None and hasattr(pool, "take_occupancy_hwm"):
        hwm["arena_occupancy"] = int(
            pool.take_occupancy_hwm(reset=reset_hwm))
    take_backlog = getattr(eng, "take_backlog_hwm", None)
    if take_backlog is not None:
        hwm["staged_backlog_rows"] = int(take_backlog(reset=reset_hwm))
    return {
        "components": comp,
        "totalBytes": sum(comp.values()),
        "inflightPrograms": len(getattr(eng, "_pending_outs", ()) or ()),
        "highWatermarks": hwm,
        "liveArrays": live_array_stats(),
        "deviceMemoryStats": backend_memory_stats(),
    }


def device_memory_payload(engine) -> dict:
    """THE document behind ``GET /api/instance/device/memory`` and the
    ``Instance.deviceMemory`` RPC: the ledger breakdown plus per-family
    compile posture (a peek — high-watermarks are NOT reset; only the
    Prometheus scrape drains them)."""
    return {**memory_ledger(engine, reset_hwm=False),
            "compileFamilies": compile_posture()}


def export_devicewatch(engine, registry=None) -> None:
    """Scrape-time export: per-family watchdog counters are already live
    in the registry — this syncs the scrape-time views (live program
    gauge, last-compile cost, the per-engine memory ledger with
    reset-on-scrape high-watermarks) and drains the query-path flight
    records into the device execution-time histogram."""
    reg = registry or REGISTRY
    inst = devicewatch_metrics(reg)
    for name, fam in WATCH.posture().items():
        inst["live"].set(fam["programsLive"], family=name)
        cost = fam["lastCost"] or {}
        if "flops" in cost:
            inst["flops"].set(cost["flops"], family=name)
        if "bytes_accessed" in cost:
            inst["bytes"].set(cost["bytes_accessed"], family=name)
    led = memory_ledger(engine, reset_hwm=True)
    lbl = getattr(engine, "metrics_label",
                  getattr(getattr(engine, "local", None), "metrics_label",
                          "e?"))
    mem = inst["mem"]
    written: set[tuple] = set()
    for comp, nbytes in led["components"].items():
        mem.set(nbytes, component=comp, engine=lbl)
        written.add(tuple(sorted({"component": comp,
                                  "engine": lbl}.items())))
    la = led["liveArrays"]
    if la is not None:
        mem.set(la["bytes"], component="live_arrays", engine=lbl)
        written.add(tuple(sorted({"component": "live_arrays",
                                  "engine": lbl}.items())))
    mem.retain(written, engine=lbl)
    mh = inst["mem_hwm"]
    kept: set[tuple] = set()
    for comp, v in led["highWatermarks"].items():
        mh.set(v, component=comp, engine=lbl)
        kept.add(tuple(sorted({"component": comp, "engine": lbl}.items())))
    mh.retain(kept, engine=lbl)
    # query-path device time: drain completed query lifecycles (the
    # ingest drain lives in metrics.harvest_slo, on the shared
    # consume-once records)
    flight = getattr(engine, "flight", None)
    if flight is not None:
        exec_hist = inst["exec"]
        for rec in flight.harvest_completed("query", terminal="device"):
            t0 = rec.stages.get("lookup", rec.t0_ns)
            t1 = rec.stages["device"]
            if t1 >= t0:
                exec_hist.observe((t1 - t0) / 1e9, family="query")


# --------------------------------------------------------------------------
# Device profiler capture
# --------------------------------------------------------------------------

_PROFILE_LOCK = threading.Lock()
_PROFILE_SEQ = [0]


def capture_device_profile(ms: float, base_dir: str | None = None) -> dict:
    """Capture a ``jax.profiler`` trace for ~``ms`` milliseconds into a
    fresh named directory and return its location + file listing. The
    profiler is a process singleton, so captures serialize on a lock;
    ``ms`` clamps to [50, 10000]. On TPU the trace carries real device
    timelines (XLA ops, HBM transfers); on CPU it still captures the
    host-side runtime — either loads in TensorBoard/Perfetto."""
    ms = max(50.0, min(float(ms), 10_000.0))
    base = base_dir or os.path.join(tempfile.gettempdir(),
                                    "swtpu-device-profiles")
    os.makedirs(base, exist_ok=True)
    with _PROFILE_LOCK:
        _PROFILE_SEQ[0] += 1
        out = os.path.join(
            base, time.strftime("prof-%Y%m%d-%H%M%S")
            + f"-p{os.getpid()}-{_PROFILE_SEQ[0]}")
        jax.profiler.start_trace(out)
        try:
            time.sleep(ms / 1000.0)
        finally:
            jax.profiler.stop_trace()
    files = []
    total = 0
    for root, _dirs, names in os.walk(out):
        for name in names:
            p = os.path.join(root, name)
            try:
                total += os.path.getsize(p)
            except OSError:
                continue
            files.append(os.path.relpath(p, out))
    return {"dir": out, "ms": ms, "files": sorted(files),
            "bytes": total}
