"""QR code generation, dependency-free (PIL only for rasterization).

The reference's service-label-generation renders QR labels for entities via
the external qrgen/zxing libraries (labels/qrcode/QrCodeGenerator.java:37-50;
SURVEY.md §2.8). No QR library ships in this image, so the encoder is
implemented here: QR model 2, byte mode, EC level M (or L), versions 1-10,
Reed-Solomon over GF(256), mask selection by penalty score — enough for
entity-URI payloads of a few hundred bytes.
"""

from __future__ import annotations

# --- GF(256) arithmetic for Reed-Solomon -------------------------------------

_EXP = [0] * 512
_LOG = [0] * 256
_x = 1
for _i in range(255):
    _EXP[_i] = _x
    _LOG[_x] = _i
    _x <<= 1
    if _x & 0x100:
        _x ^= 0x11D
for _i in range(255, 512):
    _EXP[_i] = _EXP[_i - 255]


def _gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return _EXP[_LOG[a] + _LOG[b]]


def _rs_generator(n: int) -> list[int]:
    g = [1]
    for i in range(n):
        g2 = [0] * (len(g) + 1)
        for j, c in enumerate(g):
            g2[j] ^= _gf_mul(c, _EXP[i])
            g2[j + 1] ^= c
        g = g2
    return g


def _rs_encode(data: list[int], n_ec: int) -> list[int]:
    gen = _rs_generator(n_ec)
    rem = [0] * n_ec
    for byte in data:
        factor = byte ^ rem[0]
        rem = rem[1:] + [0]
        for i, g in enumerate(gen[1:]):
            rem[i] ^= _gf_mul(factor, g)
    return rem


# --- capacity tables (versions 1-10) -----------------------------------------
# (total codewords, [EC level] -> (ec codewords per block, group1 blocks,
#  group1 data codewords, group2 blocks, group2 data codewords))

_TABLES: dict[int, dict[str, tuple[int, int, int, int, int]]] = {
    1: {"L": (7, 1, 19, 0, 0), "M": (10, 1, 16, 0, 0)},
    2: {"L": (10, 1, 34, 0, 0), "M": (16, 1, 28, 0, 0)},
    3: {"L": (15, 1, 55, 0, 0), "M": (26, 1, 44, 0, 0)},
    4: {"L": (20, 1, 80, 0, 0), "M": (18, 2, 32, 0, 0)},
    5: {"L": (26, 1, 108, 0, 0), "M": (24, 2, 43, 0, 0)},
    6: {"L": (18, 2, 68, 0, 0), "M": (16, 4, 27, 0, 0)},
    7: {"L": (20, 2, 78, 0, 0), "M": (18, 4, 31, 0, 0)},
    8: {"L": (24, 2, 97, 0, 0), "M": (22, 2, 38, 2, 39)},
    9: {"L": (30, 2, 116, 0, 0), "M": (22, 3, 36, 2, 37)},
    10: {"L": (18, 2, 68, 2, 69), "M": (26, 4, 43, 1, 44)},
}

_ALIGNMENT: dict[int, list[int]] = {
    1: [], 2: [6, 18], 3: [6, 22], 4: [6, 26], 5: [6, 30],
    6: [6, 34], 7: [6, 22, 38], 8: [6, 24, 42], 9: [6, 26, 46],
    10: [6, 28, 52],
}

_EC_BITS = {"L": 0b01, "M": 0b00}


def _choose_version(n_bytes: int, ec: str) -> int:
    for version, table in _TABLES.items():
        ecw, g1, d1, g2, d2 = table[ec]
        capacity = g1 * d1 + g2 * d2
        # byte mode header: 4 bits mode + 8 bits count (v1-9) / 16 bits (v10+)
        header_bits = 4 + (16 if version >= 10 else 8)
        if n_bytes * 8 + header_bits <= capacity * 8:
            return version
    raise ValueError(f"payload of {n_bytes} bytes exceeds QR v10/{ec} capacity")


def _encode_data(payload: bytes, version: int, ec: str) -> list[int]:
    ecw, g1, d1, g2, d2 = _TABLES[version][ec]
    n_data = g1 * d1 + g2 * d2
    bits: list[int] = []

    def push(value: int, n: int) -> None:
        for i in range(n - 1, -1, -1):
            bits.append((value >> i) & 1)

    push(0b0100, 4)  # byte mode
    push(len(payload), 16 if version >= 10 else 8)
    for b in payload:
        push(b, 8)
    push(0, min(4, n_data * 8 - len(bits)))  # terminator
    while len(bits) % 8:
        bits.append(0)
    codewords = [
        int("".join(map(str, bits[i: i + 8])), 2) for i in range(0, len(bits), 8)
    ]
    pad = (0xEC, 0x11)
    i = 0
    while len(codewords) < n_data:
        codewords.append(pad[i % 2])
        i += 1

    # split into blocks, compute EC per block, then interleave
    blocks: list[list[int]] = []
    pos = 0
    for _ in range(g1):
        blocks.append(codewords[pos: pos + d1])
        pos += d1
    for _ in range(g2):
        blocks.append(codewords[pos: pos + d2])
        pos += d2
    ec_blocks = [_rs_encode(b, ecw) for b in blocks]
    out: list[int] = []
    for i in range(max(len(b) for b in blocks)):
        for b in blocks:
            if i < len(b):
                out.append(b[i])
    for i in range(ecw):
        for b in ec_blocks:
            out.append(b[i])
    return out


def _build_matrix(version: int, data: list[int], ec: str, mask: int) -> list[list[int]]:
    size = 17 + 4 * version
    M = [[None] * size for _ in range(size)]  # None = unset

    def set_finder(r: int, c: int) -> None:
        for dr in range(-1, 8):
            for dc in range(-1, 8):
                rr, cc = r + dr, c + dc
                if 0 <= rr < size and 0 <= cc < size:
                    inside = 0 <= dr <= 6 and 0 <= dc <= 6
                    on = inside and (
                        dr in (0, 6) or dc in (0, 6) or (2 <= dr <= 4 and 2 <= dc <= 4)
                    )
                    M[rr][cc] = 1 if on else 0

    set_finder(0, 0)
    set_finder(0, size - 7)
    set_finder(size - 7, 0)

    # timing patterns
    for i in range(8, size - 8):
        v = 1 if i % 2 == 0 else 0
        if M[6][i] is None:
            M[6][i] = v
        if M[i][6] is None:
            M[i][6] = v

    # alignment patterns
    centers = _ALIGNMENT[version]
    for r in centers:
        for c in centers:
            if M[r][c] is not None:
                continue
            for dr in range(-2, 3):
                for dc in range(-2, 3):
                    on = max(abs(dr), abs(dc)) != 1
                    M[r + dr][c + dc] = 1 if on else 0

    # reserve format info areas + dark module
    for i in range(9):
        if M[8][i] is None:
            M[8][i] = 0
        if M[i][8] is None:
            M[i][8] = 0
    for i in range(8):
        if M[8][size - 1 - i] is None:
            M[8][size - 1 - i] = 0
        if M[size - 1 - i][8] is None:
            M[size - 1 - i][8] = 0
    M[size - 8][8] = 1  # dark module

    # place data bits in the serpentine column pairs
    bits: list[int] = []
    for byte in data:
        for i in range(7, -1, -1):
            bits.append((byte >> i) & 1)
    bit_i = 0
    col = size - 1
    upward = True
    while col > 0:
        if col == 6:
            col -= 1
        rows = range(size - 1, -1, -1) if upward else range(size)
        for r in rows:
            for c in (col, col - 1):
                if M[r][c] is None:
                    bit = bits[bit_i] if bit_i < len(bits) else 0
                    bit_i += 1
                    if _mask_on(mask, r, c):
                        bit ^= 1
                    M[r][c] = bit
        upward = not upward
        col -= 2

    _place_format_info(M, size, ec, mask)
    return M


def _mask_on(mask: int, r: int, c: int) -> bool:
    if mask == 0:
        return (r + c) % 2 == 0
    if mask == 1:
        return r % 2 == 0
    if mask == 2:
        return c % 3 == 0
    if mask == 3:
        return (r + c) % 3 == 0
    if mask == 4:
        return (r // 2 + c // 3) % 2 == 0
    if mask == 5:
        return (r * c) % 2 + (r * c) % 3 == 0
    if mask == 6:
        return ((r * c) % 2 + (r * c) % 3) % 2 == 0
    return ((r + c) % 2 + (r * c) % 3) % 2 == 0


def _place_format_info(M: list[list[int]], size: int, ec: str, mask: int) -> None:
    fmt = (_EC_BITS[ec] << 3) | mask
    # BCH(15,5) with generator 0x537, then XOR mask 0x5412
    val = fmt << 10
    g = 0b10100110111
    for i in range(14, 9, -1):
        if val >> i & 1:
            val ^= g << (i - 10)
    bits15 = ((fmt << 10) | val) ^ 0x5412
    fb = [(bits15 >> i) & 1 for i in range(14, -1, -1)]
    # around the top-left finder
    coords_a = [(8, 0), (8, 1), (8, 2), (8, 3), (8, 4), (8, 5), (8, 7), (8, 8),
                (7, 8), (5, 8), (4, 8), (3, 8), (2, 8), (1, 8), (0, 8)]
    for (r, c), b in zip(coords_a, fb):
        M[r][c] = b
    # split copy: below bottom-left + right of top-right
    coords_b = [(size - 1, 8), (size - 2, 8), (size - 3, 8), (size - 4, 8),
                (size - 5, 8), (size - 6, 8), (size - 7, 8),
                (8, size - 8), (8, size - 7), (8, size - 6), (8, size - 5),
                (8, size - 4), (8, size - 3), (8, size - 2), (8, size - 1)]
    for (r, c), b in zip(coords_b, fb):
        M[r][c] = b


def _penalty(M: list[list[int]]) -> int:
    size = len(M)
    score = 0
    for rows in (M, list(map(list, zip(*M)))):  # rows then columns
        for row in rows:
            run = 1
            for i in range(1, size):
                if row[i] == row[i - 1]:
                    run += 1
                else:
                    if run >= 5:
                        score += 3 + run - 5
                    run = 1
            if run >= 5:
                score += 3 + run - 5
    for r in range(size - 1):
        for c in range(size - 1):
            if M[r][c] == M[r][c + 1] == M[r + 1][c] == M[r + 1][c + 1]:
                score += 3
    pattern = [1, 0, 1, 1, 1, 0, 1, 0, 0, 0, 0]
    for seq in (pattern, pattern[::-1]):
        for r in range(size):
            for c in range(size - 10):
                if [M[r][c + i] for i in range(11)] == seq:
                    score += 40
                if [M[c + i][r] for i in range(11)] == seq:
                    score += 40
    dark = sum(sum(row) for row in M)
    ratio = dark * 100 // (size * size)
    score += abs(ratio - 50) // 5 * 10
    return score


def qr_matrix(payload: bytes | str, ec: str = "M") -> list[list[int]]:
    """Encode payload into a QR module matrix (1 = dark)."""
    if isinstance(payload, str):
        payload = payload.encode()
    version = _choose_version(len(payload), ec)
    data = _encode_data(payload, version, ec)
    best, best_score = None, None
    for mask in range(8):
        M = _build_matrix(version, data, ec, mask)
        s = _penalty(M)
        if best_score is None or s < best_score:
            best, best_score = M, s
    return best


def qr_png(payload: bytes | str, scale: int = 8, border: int = 4,
           ec: str = "M") -> bytes:
    """Render a QR code to PNG bytes (PIL)."""
    import io

    from PIL import Image

    M = qr_matrix(payload, ec)
    size = len(M)
    img = Image.new("1", ((size + 2 * border) * scale,) * 2, 1)
    px = img.load()
    for r in range(size):
        for c in range(size):
            if M[r][c]:
                for dr in range(scale):
                    for dc in range(scale):
                        px[(c + border) * scale + dc, (r + border) * scale + dr] = 0
    buf = io.BytesIO()
    img.save(buf, format="PNG")
    return buf.getvalue()
