"""Label generation service (reference: service-label-generation —
LabelGeneratorManager + DefaultEntityUriProvider + QrCodeGenerator;
SURVEY.md §2.8). Generates QR labels from canonical entity URIs for
devices / assets / areas / customers / device groups.
"""

from __future__ import annotations

from typing import Callable

from sitewhere_tpu.labels.qrcode import qr_png


class EntityUriProvider:
    """Canonical sitewhere entity URIs (DefaultEntityUriProvider analog)."""

    def __init__(self, instance: str = "sitewhere-tpu"):
        self.instance = instance

    def _uri(self, kind: str, token: str) -> str:
        return f"sitewhere://{self.instance}/{kind}/{token}"

    def device_uri(self, token: str) -> str:
        return self._uri("device", token)

    def assignment_uri(self, aid: int) -> str:
        return self._uri("assignment", str(aid))

    def asset_uri(self, token: str) -> str:
        return self._uri("asset", token)

    def area_uri(self, token: str) -> str:
        return self._uri("area", token)

    def customer_uri(self, token: str) -> str:
        return self._uri("customer", token)

    def device_group_uri(self, token: str) -> str:
        return self._uri("devicegroup", token)


class QrCodeGenerator:
    """One label generator (reference: labels/qrcode/QrCodeGenerator.java)."""

    generator_id = "qrcode"
    name = "QR Code Generator"

    def __init__(self, uris: EntityUriProvider | None = None, scale: int = 8):
        self.uris = uris or EntityUriProvider()
        self.scale = scale

    def _png(self, uri: str) -> bytes:
        return qr_png(uri, scale=self.scale)

    def device_label(self, token: str) -> bytes:
        return self._png(self.uris.device_uri(token))

    def asset_label(self, token: str) -> bytes:
        return self._png(self.uris.asset_uri(token))

    def area_label(self, token: str) -> bytes:
        return self._png(self.uris.area_uri(token))

    def customer_label(self, token: str) -> bytes:
        return self._png(self.uris.customer_uri(token))

    def device_group_label(self, token: str) -> bytes:
        return self._png(self.uris.device_group_uri(token))


class LabelGeneratorManager:
    """Registry of named generators (LabelGeneratorManager analog)."""

    def __init__(self):
        self.generators: dict[str, QrCodeGenerator] = {}
        self.register(QrCodeGenerator())

    def register(self, generator) -> None:
        self.generators[generator.generator_id] = generator

    def get(self, generator_id: str):
        gen = self.generators.get(generator_id)
        if gen is None:
            raise KeyError(f"label generator {generator_id!r} not found")
        return gen

    def list_generators(self) -> list[dict]:
        return [
            {"id": g.generator_id, "name": g.name}
            for g in self.generators.values()
        ]
