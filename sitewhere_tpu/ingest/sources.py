"""Inbound event sources: receivers + decoder + deduplicator.

Mirrors the reference's ingestion edge (SURVEY.md §2.1):
``InboundEventSource`` binds N protocol receivers to one decoder and an
optional deduplicator (sources/InboundEventSource.java:35-298 —
onEncodedEventReceived -> decodePayload -> dedup -> forward, decode/failure/
duplicate counters at lines 50-59, 233-246); ``EventSourcesManager`` parses
source configs, owns the forward path, and splits decoded requests into
event-create vs device-registration flows with a failed-decode dead letter
(sources/manager/EventSourcesManager.java:38-260, branch at 167-205, DLQ at
212-220).

Receivers here are asyncio servers/clients (TCP socket, WebSocket, REST
polling, in-memory; MQTT in ingest/mqtt.py, CoAP in ingest/coap.py) — the
thread-pool receiver model of the reference (MqttInboundEventReceiver.java:
56-79) becomes event-loop concurrency.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Callable

from sitewhere_tpu.ingest.decoders import EventDecoder
from sitewhere_tpu.ingest.dedup import Deduplicator
from sitewhere_tpu.ingest.requests import DecodedRequest, EventDecodeException, RequestType
from sitewhere_tpu.utils.lifecycle import LifecycleComponent

logger = logging.getLogger(__name__)


class InboundEventReceiver(LifecycleComponent):
    """Base protocol receiver; concrete receivers call ``submit``."""

    def __init__(self, name: str | None = None, required: bool = True):
        super().__init__(name, required)
        self.source: "InboundEventSource | None" = None

    def bind(self, source: "InboundEventSource") -> None:
        self.source = source

    def submit(self, payload: bytes, metadata: dict[str, Any] | None = None,
               on_durable: Callable[[], Any] | None = None) -> int:
        assert self.source is not None, "receiver not bound to a source"
        return self.source.on_encoded_event_received(payload, metadata or {},
                                                     on_durable=on_durable)


class InboundEventSource(LifecycleComponent):
    """One event source: receivers -> decoder -> dedup -> manager."""

    def __init__(
        self,
        source_id: str,
        decoder: EventDecoder,
        receivers: list[InboundEventReceiver] | None = None,
        deduplicator: Deduplicator | None = None,
        tenant: str = "default",
        batcher=None,
    ):
        super().__init__(f"event-source:{source_id}")
        self.source_id = source_id
        self.decoder = decoder
        self.deduplicator = deduplicator
        self.tenant = tenant
        self.manager: "EventSourcesManager | None" = None
        self.receivers = receivers or []
        for r in self.receivers:
            r.bind(self)
            self.add_child(r)
        # batched arena submission (ingest/wire_edge.WireBatcher): when
        # the decoder declares a wire_tag the raw payload skips host-side
        # decode and rides the engine's batch-ingest facade, one engine
        # call per arrival window instead of one lock acquisition per
        # event. A host-side deduplicator forces the per-payload path —
        # dedup needs the decoded alternate id (the wire edge's own
        # socket endpoints dedup by byte scan instead).
        self.batcher = batcher
        self._wire_tag = getattr(decoder, "wire_tag", None)
        if batcher is not None and deduplicator is not None:
            raise ValueError(
                "batched submission and a host-side deduplicator are "
                "mutually exclusive; drop one of them")
        # Prometheus-analog counters (InboundEventSource.java:50-59)
        self.decoded_count = 0
        self.failed_count = 0
        self.duplicate_count = 0
        self.batched_count = 0

    def on_encoded_event_received(self, payload: bytes, metadata: dict[str, Any],
                                  on_durable: Callable[[], Any] | None = None) -> int:
        """Forward one raw payload; returns number of requests forwarded.

        Batched mode (``batcher`` set + batchable decoder): the payload is
        appended to the shared arrival window by reference and decoded by
        the engine's native scanner inside the staging arena — decode
        failures are then counted by the engine's batch summary rather
        than this source's ``failed_count``/dead letter.

        ``on_durable`` fires once the payload's batch has cleared the WAL
        durability gate (batched mode; it runs on the flusher thread — the
        receiver marshals back to its own loop). On the per-payload path
        the forward is synchronous, so the callback fires before return."""
        assert self.manager is not None, "source not attached to a manager"
        if self.batcher is not None and self._wire_tag is not None:
            if isinstance(payload, str):
                payload = payload.encode()
            self.batcher.add(payload, tenant=self.tenant,
                             binary=self._wire_tag == "binary",
                             on_durable=on_durable)
            self.batched_count += 1
            return 1
        metadata = {**metadata, "source_id": self.source_id}
        try:
            requests = self.decoder.decode(payload, metadata)
        except EventDecodeException as e:
            self.failed_count += 1
            self.manager.on_decode_failed(self.source_id, payload, metadata, e)
            if on_durable is not None:
                on_durable()
            return 0
        forwarded = 0
        for req in requests:
            if req.tenant == "default":
                req.tenant = self.tenant
            if self.deduplicator is not None and self.deduplicator.is_duplicate(req):
                self.duplicate_count += 1
                continue
            self.decoded_count += 1
            self.manager.on_decoded_request(self.source_id, req)
            forwarded += 1
        if on_durable is not None:
            on_durable()
        return forwarded


class EventSourcesManager(LifecycleComponent):
    """Owns all sources for a tenant engine; routes decoded requests.

    ``on_event_request`` receives event-create requests (the decoded-events
    Kafka topic analog) and ``on_registration_request`` receives registration
    requests (the device-registration topic analog). Failed decodes land in a
    bounded in-memory dead letter, mirroring the failed-decode topic."""

    def __init__(
        self,
        on_event_request: Callable[[DecodedRequest], None],
        on_registration_request: Callable[[DecodedRequest], None] | None = None,
        dead_letter_capacity: int = 4096,
        batcher=None,
    ):
        super().__init__("event-sources-manager")
        self.sources: dict[str, InboundEventSource] = {}
        self._on_event = on_event_request
        self._on_register = on_registration_request
        self.failed_decodes: list[tuple[str, bytes, str]] = []
        self.dead_letter_capacity = dead_letter_capacity
        # shared batched-submit accumulator (ingest/wire_edge.WireBatcher):
        # newly added sources with a batchable decoder and no host-side
        # deduplicator inherit it, so CoAP/polling/in-memory receivers pay
        # one engine call per arrival window, not one per event
        self.batcher = batcher

    def add_source(self, source: InboundEventSource) -> InboundEventSource:
        if source.source_id in self.sources:
            raise ValueError(f"duplicate source id {source.source_id!r}")
        self.sources[source.source_id] = source
        source.manager = self
        if (self.batcher is not None and source.batcher is None
                and source._wire_tag is not None
                and source.deduplicator is None):
            source.batcher = self.batcher
        self.add_child(source)
        return source

    async def on_stop(self) -> None:
        """Drain the shared arrival window so every accepted payload
        reaches the engine before the sources report stopped."""
        if self.batcher is not None:
            self.batcher.flush()

    def on_decoded_request(self, source_id: str, req: DecodedRequest) -> None:
        if req.type is RequestType.REGISTER_DEVICE and self._on_register is not None:
            self._on_register(req)
        else:
            self._on_event(req)

    def on_decode_failed(self, source_id: str, payload: bytes,
                         metadata: dict, error: Exception) -> None:
        if len(self.failed_decodes) < self.dead_letter_capacity:
            self.failed_decodes.append((source_id, payload, str(error)))
        logger.warning("decode failed on %s: %s", source_id, error)


# --- concrete receivers ------------------------------------------------------


class InMemoryEventReceiver(InboundEventReceiver):
    """Direct-submit receiver for tests, benchmarks, and embedded use."""

    def __init__(self, name: str = "inmemory"):
        super().__init__(name)


class SocketEventReceiver(InboundEventReceiver):
    """Raw TCP socket receiver (reference: sources/socket/
    SocketInboundEventReceiver.java + interaction handlers). Framing modes:
    ``read_all`` (one payload per connection), ``length_prefixed`` (u32 BE
    length frames), ``newline`` (one payload per line)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 framing: str = "read_all"):
        super().__init__(f"socket:{port}")
        if framing not in ("read_all", "length_prefixed", "newline"):
            raise ValueError(f"unknown framing {framing!r}")
        self.host, self.port, self.framing = host, port, framing
        self._server: asyncio.AbstractServer | None = None

    @property
    def bound_port(self) -> int:
        assert self._server is not None and self._server.sockets
        return self._server.sockets[0].getsockname()[1]

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        peer = writer.get_extra_info("peername")
        meta = {"remote": str(peer)}
        try:
            if self.framing == "read_all":
                payload = await reader.read(-1)
                if payload:
                    self.submit(payload, meta)
            elif self.framing == "length_prefixed":
                while True:
                    header = await reader.readexactly(4)
                    n = int.from_bytes(header, "big")
                    payload = await reader.readexactly(n)
                    self.submit(payload, meta)
            else:  # newline
                while True:
                    line = await reader.readline()
                    if not line:
                        break
                    line = line.strip()
                    if line:
                        self.submit(line, meta)
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()

    async def on_start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)

    async def on_stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None


class WebSocketEventReceiver(InboundEventReceiver):
    """WebSocket receiver for binary or text payloads (reference:
    sources/websocket/{Binary,String}WebSocketEventReceiver.java)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        super().__init__(f"websocket:{port}")
        self.host, self.port = host, port
        self._server = None

    @property
    def bound_port(self) -> int:
        assert self._server is not None
        return next(iter(self._server.sockets)).getsockname()[1]

    async def _handle(self, ws) -> None:
        async for message in ws:
            payload = message.encode() if isinstance(message, str) else message
            self.submit(payload, {"remote": str(ws.remote_address)})

    async def on_start(self) -> None:
        import websockets

        self._server = await websockets.serve(self._handle, self.host, self.port)

    async def on_stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None


class PollingRestReceiver(InboundEventReceiver):
    """Poll a REST endpoint on an interval and submit the response body
    (reference: sources/rest/PollingRestInboundEventReceiver.java)."""

    def __init__(self, url: str, interval_s: float = 10.0,
                 headers: dict[str, str] | None = None):
        super().__init__(f"rest-poll:{url}")
        self.url = url
        self.interval_s = interval_s
        self.headers = headers or {}
        self._task: asyncio.Task | None = None

    async def _poll_loop(self) -> None:
        import aiohttp

        async with aiohttp.ClientSession() as session:
            while True:
                try:
                    async with session.get(self.url, headers=self.headers) as resp:
                        body = await resp.read()
                        if resp.status == 200 and body:
                            self.submit(body, {"url": self.url})
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    logger.warning("poll %s failed: %s", self.url, e)
                await asyncio.sleep(self.interval_s)

    async def on_start(self) -> None:
        self._task = asyncio.create_task(self._poll_loop())

    async def on_stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
