"""Native MQTT 3.1.1: wire codec, asyncio client, embedded broker, receiver.

The reference's primary ingest protocol is MQTT via the fusesource client
(sources/mqtt/MqttInboundEventReceiver.java:40-120 — subscribe thread +
processor pool, QoS 0/1/2) and it also embeds an ActiveMQ broker for
broker-style sources (sources/activemq/ActiveMqBrokerEventReceiver). No MQTT
library ships in this image, so the protocol is implemented here: a minimal,
dependency-free MQTT 3.1.1 subset (CONNECT/CONNACK, PUBLISH QoS 0/1 with
PUBACK, SUBSCRIBE/SUBACK, PING, DISCONNECT) sufficient for telemetry ingest,
command downlink publishing (commands/destinations.py), and an embedded
broker used by tests and the load generator.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Callable

from sitewhere_tpu.ingest.sources import InboundEventReceiver

logger = logging.getLogger(__name__)

# control packet types
CONNECT, CONNACK, PUBLISH, PUBACK = 1, 2, 3, 4
SUBSCRIBE, SUBACK, UNSUBSCRIBE, UNSUBACK = 8, 9, 10, 11
PINGREQ, PINGRESP, DISCONNECT = 12, 13, 14


def encode_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n % 128
        n //= 128
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


async def read_varint(reader: asyncio.StreamReader) -> int:
    mult, value = 1, 0
    for _ in range(4):
        (b,) = await reader.readexactly(1)
        value += (b & 0x7F) * mult
        if not b & 0x80:
            return value
        mult *= 128
    raise ValueError("malformed remaining-length varint")


def _utf8(s: str) -> bytes:
    b = s.encode()
    return len(b).to_bytes(2, "big") + b


def encode_packet(ptype: int, flags: int, payload: bytes) -> bytes:
    return bytes([(ptype << 4) | flags]) + encode_varint(len(payload)) + payload


async def read_packet(reader: asyncio.StreamReader) -> tuple[int, int, bytes]:
    (h,) = await reader.readexactly(1)
    length = await read_varint(reader)
    body = await reader.readexactly(length) if length else b""
    return h >> 4, h & 0x0F, body


def encode_connect(client_id: str, keepalive: int = 60,
                   username: str | None = None, password: str | None = None) -> bytes:
    flags = 0x02  # clean session
    tail = _utf8(client_id)
    if username is not None:
        flags |= 0x80
        tail += _utf8(username)
    if password is not None:
        flags |= 0x40
        tail += _utf8(password)
    var = _utf8("MQTT") + bytes([4, flags]) + keepalive.to_bytes(2, "big")
    return encode_packet(CONNECT, 0, var + tail)


def encode_publish(topic: str, payload: bytes, qos: int = 0, packet_id: int = 1) -> bytes:
    var = _utf8(topic)
    if qos:
        var += packet_id.to_bytes(2, "big")
    return encode_packet(PUBLISH, qos << 1, var + payload)


def decode_publish(flags: int, body: bytes) -> tuple[str, bytes, int, int]:
    qos = (flags >> 1) & 0x03
    tlen = int.from_bytes(body[:2], "big")
    topic = body[2: 2 + tlen].decode()
    off = 2 + tlen
    packet_id = 0
    if qos:
        packet_id = int.from_bytes(body[off: off + 2], "big")
        off += 2
    return topic, body[off:], qos, packet_id


def encode_subscribe(packet_id: int, topics: list[tuple[str, int]]) -> bytes:
    payload = packet_id.to_bytes(2, "big")
    for topic, qos in topics:
        payload += _utf8(topic) + bytes([qos])
    return encode_packet(SUBSCRIBE, 0x02, payload)


def topic_matches(pattern: str, topic: str) -> bool:
    """MQTT wildcard matching: ``+`` one level, ``#`` trailing multi-level."""
    pp, tp = pattern.split("/"), topic.split("/")
    for i, seg in enumerate(pp):
        if seg == "#":
            return True
        if i >= len(tp):
            return False
        if seg != "+" and seg != tp[i]:
            return False
    return len(pp) == len(tp)


class MqttClient:
    """Minimal asyncio MQTT 3.1.1 client (QoS 0/1)."""

    def __init__(self, host: str, port: int, client_id: str = "sitewhere-tpu",
                 username: str | None = None, password: str | None = None,
                 keepalive: int = 60):
        self.host, self.port = host, port
        self.client_id = client_id
        self.username, self.password = username, password
        self.keepalive = keepalive
        self.on_message: Callable[[str, bytes], Any] | None = None
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._packet_id = 0
        self._task: asyncio.Task | None = None
        self._acks: dict[int, asyncio.Future] = {}
        self._ping_task: asyncio.Task | None = None

    def _next_id(self) -> int:
        self._packet_id = self._packet_id % 0xFFFF + 1
        return self._packet_id

    async def connect(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(self.host, self.port)
        self._writer.write(encode_connect(self.client_id, self.keepalive,
                                          self.username, self.password))
        await self._writer.drain()
        ptype, _, body = await read_packet(self._reader)
        if ptype != CONNACK or body[1] != 0:
            raise ConnectionError(f"MQTT connect refused: {body!r}")
        self._task = asyncio.create_task(self._read_loop())
        if self.keepalive:
            self._ping_task = asyncio.create_task(self._ping_loop())

    async def _ping_loop(self) -> None:
        while True:
            await asyncio.sleep(max(self.keepalive - 5, 5))
            self._writer.write(encode_packet(PINGREQ, 0, b""))
            await self._writer.drain()

    async def _read_loop(self) -> None:
        try:
            while True:
                ptype, flags, body = await read_packet(self._reader)
                if ptype == PUBLISH:
                    topic, payload, qos, pid = decode_publish(flags, body)
                    if qos == 1:
                        self._writer.write(
                            encode_packet(PUBACK, 0, pid.to_bytes(2, "big"))
                        )
                        await self._writer.drain()
                    if self.on_message is not None:
                        res = self.on_message(topic, payload)
                        if asyncio.iscoroutine(res):
                            await res
                elif ptype in (PUBACK, SUBACK, UNSUBACK):
                    pid = int.from_bytes(body[:2], "big")
                    fut = self._acks.pop(pid, None)
                    if fut is not None and not fut.done():
                        fut.set_result(body)
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            pass

    async def subscribe(self, topic: str, qos: int = 0) -> None:
        pid = self._next_id()
        fut = asyncio.get_running_loop().create_future()
        self._acks[pid] = fut
        self._writer.write(encode_subscribe(pid, [(topic, qos)]))
        await self._writer.drain()
        await asyncio.wait_for(fut, 10)

    async def publish(self, topic: str, payload: bytes, qos: int = 0) -> None:
        pid = self._next_id() if qos else 0
        if qos:
            fut = asyncio.get_running_loop().create_future()
            self._acks[pid] = fut
        self._writer.write(encode_publish(topic, payload, qos, pid))
        await self._writer.drain()
        if qos:
            await asyncio.wait_for(fut, 10)

    async def disconnect(self) -> None:
        for t in (self._ping_task, self._task):
            if t is not None:
                t.cancel()
        if self._writer is not None:
            try:
                self._writer.write(encode_packet(DISCONNECT, 0, b""))
                await self._writer.drain()
            except ConnectionError:
                pass
            self._writer.close()


class MqttBroker:
    """Embedded MQTT broker (QoS 0/1 fan-out, wildcard subscriptions) — the
    analog of the reference's embedded ActiveMQ broker receiver
    (sources/activemq/ActiveMqBrokerEventReceiver.java), and the test/load
    harness for MQTT paths."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host, self.port = host, port
        self._server: asyncio.AbstractServer | None = None
        # writer -> list of subscription patterns
        self._subs: dict[asyncio.StreamWriter, list[str]] = {}

    @property
    def bound_port(self) -> int:
        assert self._server is not None and self._server.sockets
        return self._server.sockets[0].getsockname()[1]

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)

    async def stop(self) -> None:
        # close live client connections BEFORE wait_closed(): in Python 3.12
        # Server.wait_closed() blocks until every connection handler returns
        for w in list(self._subs):
            w.close()
        self._subs.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            ptype, _, _ = await read_packet(reader)
            if ptype != CONNECT:
                writer.close()
                return
            writer.write(encode_packet(CONNACK, 0, b"\x00\x00"))
            await writer.drain()
            self._subs[writer] = []
            while True:
                ptype, flags, body = await read_packet(reader)
                if ptype == PUBLISH:
                    topic, payload, qos, pid = decode_publish(flags, body)
                    if qos:
                        writer.write(encode_packet(PUBACK, 0, pid.to_bytes(2, "big")))
                        await writer.drain()
                    await self._fanout(topic, payload)
                elif ptype == SUBSCRIBE:
                    pid = int.from_bytes(body[:2], "big")
                    off, grants = 2, []
                    while off < len(body):
                        tlen = int.from_bytes(body[off: off + 2], "big")
                        topic = body[off + 2: off + 2 + tlen].decode()
                        qos = body[off + 2 + tlen]
                        off += 3 + tlen
                        self._subs[writer].append(topic)
                        grants.append(min(qos, 1))
                    writer.write(
                        encode_packet(SUBACK, 0, pid.to_bytes(2, "big") + bytes(grants))
                    )
                    await writer.drain()
                elif ptype == PINGREQ:
                    writer.write(encode_packet(PINGRESP, 0, b""))
                    await writer.drain()
                elif ptype == DISCONNECT:
                    break
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            self._subs.pop(writer, None)
            writer.close()

    async def _fanout(self, topic: str, payload: bytes) -> None:
        pkt = encode_publish(topic, payload, 0, 0)
        for w, patterns in list(self._subs.items()):
            if any(topic_matches(p, topic) for p in patterns):
                try:
                    w.write(pkt)
                    await w.drain()
                except ConnectionError:
                    self._subs.pop(w, None)


class MqttEventReceiver(InboundEventReceiver):
    """Subscribe to a broker topic and submit payloads to the event source
    (reference: sources/mqtt/MqttInboundEventReceiver.java)."""

    def __init__(self, host: str, port: int, topic: str = "sitewhere/input/#",
                 qos: int = 0, client_id: str = "sw-ingest",
                 username: str | None = None, password: str | None = None):
        super().__init__(f"mqtt:{topic}")
        self.topic, self.qos = topic, qos
        self.client = MqttClient(host, port, client_id, username, password)

    async def on_start(self) -> None:
        self.client.on_message = lambda topic, payload: self.submit(
            payload, {"topic": topic}
        )
        await self.client.connect()
        await self.client.subscribe(self.topic, self.qos)

    async def on_stop(self) -> None:
        await self.client.disconnect()
