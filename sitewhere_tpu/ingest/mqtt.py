"""Native MQTT 3.1.1: wire codec, asyncio client, embedded broker, receiver.

The reference's primary ingest protocol is MQTT via the fusesource client
(sources/mqtt/MqttInboundEventReceiver.java:40-120 — subscribe thread +
processor pool, QoS 0/1/2) and it also embeds an ActiveMQ broker for
broker-style sources (sources/activemq/ActiveMqBrokerEventReceiver). No MQTT
library ships in this image, so the protocol is implemented here: a minimal,
dependency-free MQTT 3.1.1 subset (CONNECT/CONNACK, PUBLISH QoS 0/1 with
PUBACK, SUBSCRIBE/SUBACK, PING, DISCONNECT) sufficient for telemetry ingest,
command downlink publishing (commands/destinations.py), and an embedded
broker used by tests and the load generator.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Callable

from sitewhere_tpu.ingest.sources import InboundEventReceiver

logger = logging.getLogger(__name__)

# control packet types
CONNECT, CONNACK, PUBLISH, PUBACK = 1, 2, 3, 4
PUBREC, PUBREL, PUBCOMP = 5, 6, 7
SUBSCRIBE, SUBACK, UNSUBSCRIBE, UNSUBACK = 8, 9, 10, 11
PINGREQ, PINGRESP, DISCONNECT = 12, 13, 14


def encode_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n % 128
        n //= 128
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


async def read_varint(reader: asyncio.StreamReader) -> int:
    mult, value = 1, 0
    for _ in range(4):
        (b,) = await reader.readexactly(1)
        value += (b & 0x7F) * mult
        if not b & 0x80:
            return value
        mult *= 128
    raise ValueError("malformed remaining-length varint")


def _utf8(s: str) -> bytes:
    b = s.encode()
    return len(b).to_bytes(2, "big") + b


def encode_packet(ptype: int, flags: int, payload: bytes) -> bytes:
    return bytes([(ptype << 4) | flags]) + encode_varint(len(payload)) + payload


async def read_packet(reader: asyncio.StreamReader) -> tuple[int, int, bytes]:
    (h,) = await reader.readexactly(1)
    length = await read_varint(reader)
    body = await reader.readexactly(length) if length else b""
    return h >> 4, h & 0x0F, body


class FrameTooLarge(ValueError):
    """Remaining-length exceeds the receiver's frame budget; the packet
    body was deliberately NOT consumed (callers close the connection)."""


async def read_packet_limited(reader: asyncio.StreamReader,
                              max_bytes: int) -> tuple[int, int, bytes]:
    """Server-side :func:`read_packet` with an oversized-frame guard: the
    remaining-length varint is checked BEFORE the body read, so a hostile
    or misconfigured client can never make the edge buffer an arbitrarily
    large packet (ingest/wire_edge.py counts these as ``frames_invalid``)."""
    (h,) = await reader.readexactly(1)
    length = await read_varint(reader)
    if length > max_bytes:
        raise FrameTooLarge(f"remaining length {length} > {max_bytes}")
    body = await reader.readexactly(length) if length else b""
    return h >> 4, h & 0x0F, body


def decode_connect(body: bytes) -> tuple[str, int]:
    """Parse a CONNECT variable header + payload into
    ``(client_id, keepalive_s)``; raises ``ValueError`` on malformed input
    (the wire edge counts and disconnects)."""
    nlen = int.from_bytes(body[:2], "big")
    if body[2: 2 + nlen] != b"MQTT":
        raise ValueError(f"bad protocol name {body[2: 2 + nlen]!r}")
    off = 2 + nlen + 2          # name + level byte + connect flags
    keepalive = int.from_bytes(body[off: off + 2], "big")
    off += 2
    idlen = int.from_bytes(body[off: off + 2], "big")
    client_id = body[off + 2: off + 2 + idlen].decode()
    return client_id, keepalive


def encode_connect(client_id: str, keepalive: int = 60,
                   username: str | None = None, password: str | None = None) -> bytes:
    flags = 0x02  # clean session
    tail = _utf8(client_id)
    if username is not None:
        flags |= 0x80
        tail += _utf8(username)
    if password is not None:
        flags |= 0x40
        tail += _utf8(password)
    var = _utf8("MQTT") + bytes([4, flags]) + keepalive.to_bytes(2, "big")
    return encode_packet(CONNECT, 0, var + tail)


def encode_publish(topic: str, payload: bytes, qos: int = 0, packet_id: int = 1) -> bytes:
    var = _utf8(topic)
    if qos:
        var += packet_id.to_bytes(2, "big")
    return encode_packet(PUBLISH, qos << 1, var + payload)


def decode_publish(flags: int, body: bytes) -> tuple[str, bytes, int, int]:
    qos = (flags >> 1) & 0x03
    tlen = int.from_bytes(body[:2], "big")
    topic = body[2: 2 + tlen].decode()
    off = 2 + tlen
    packet_id = 0
    if qos:
        packet_id = int.from_bytes(body[off: off + 2], "big")
        off += 2
    return topic, body[off:], qos, packet_id


def encode_subscribe(packet_id: int, topics: list[tuple[str, int]]) -> bytes:
    payload = packet_id.to_bytes(2, "big")
    for topic, qos in topics:
        payload += _utf8(topic) + bytes([qos])
    return encode_packet(SUBSCRIBE, 0x02, payload)


def topic_matches(pattern: str, topic: str) -> bool:
    """MQTT wildcard matching: ``+`` one level, ``#`` trailing multi-level."""
    pp, tp = pattern.split("/"), topic.split("/")
    for i, seg in enumerate(pp):
        if seg == "#":
            return True
        if i >= len(tp):
            return False
        if seg != "+" and seg != tp[i]:
            return False
    return len(pp) == len(tp)


class MqttClient:
    """Minimal asyncio MQTT 3.1.1 client (QoS 0/1/2).

    QoS 2 implements both halves of the exactly-once handshake
    (reference parity: MqttInboundEventReceiver.java:111-120 maps
    EXACTLY_ONCE): outbound PUBLISH -> PUBREC -> PUBREL -> PUBCOMP, and
    inbound PUBLISH(qos2) deduplicated by packet id until the sender's
    PUBREL releases it."""

    def __init__(self, host: str, port: int, client_id: str = "sitewhere-tpu",
                 username: str | None = None, password: str | None = None,
                 keepalive: int = 60):
        self.host, self.port = host, port
        self.client_id = client_id
        self.username, self.password = username, password
        self.keepalive = keepalive
        self.on_message: Callable[[str, bytes], Any] | None = None
        self.on_disconnect: Callable[[], Any] | None = None
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._packet_id = 0
        self._task: asyncio.Task | None = None
        self._acks: dict[int, asyncio.Future] = {}
        self._ping_task: asyncio.Task | None = None
        self._inbound_qos2: set[int] = set()   # pids seen, awaiting PUBREL
        self._closing = False

    def _next_id(self) -> int:
        self._packet_id = self._packet_id % 0xFFFF + 1
        return self._packet_id

    async def connect(self) -> None:
        # fresh session state (clean-session connect; also reused by the
        # receiver's reconnect path)
        self._closing = False
        self._acks.clear()
        self._inbound_qos2.clear()
        self._reader, self._writer = await asyncio.open_connection(self.host, self.port)
        self._writer.write(encode_connect(self.client_id, self.keepalive,
                                          self.username, self.password))
        await self._writer.drain()
        ptype, _, body = await read_packet(self._reader)
        if ptype != CONNACK or body[1] != 0:
            raise ConnectionError(f"MQTT connect refused: {body!r}")
        self._task = asyncio.create_task(self._read_loop())
        if self.keepalive:
            self._ping_task = asyncio.create_task(self._ping_loop())

    async def _ping_loop(self) -> None:
        while True:
            await asyncio.sleep(max(self.keepalive - 5, 5))
            self._writer.write(encode_packet(PINGREQ, 0, b""))
            await self._writer.drain()

    async def _read_loop(self) -> None:
        try:
            while True:
                ptype, flags, body = await read_packet(self._reader)
                if ptype == PUBLISH:
                    topic, payload, qos, pid = decode_publish(flags, body)
                    deliver = True
                    if qos == 1:
                        self._writer.write(
                            encode_packet(PUBACK, 0, pid.to_bytes(2, "big"))
                        )
                        await self._writer.drain()
                    elif qos == 2:
                        # exactly-once receive: a redelivered PUBLISH with
                        # the same pid (sender never saw our PUBREC) must
                        # not reach the application twice
                        deliver = pid not in self._inbound_qos2
                        self._inbound_qos2.add(pid)
                        self._writer.write(
                            encode_packet(PUBREC, 0, pid.to_bytes(2, "big"))
                        )
                        await self._writer.drain()
                    if deliver and self.on_message is not None:
                        res = self.on_message(topic, payload)
                        if asyncio.iscoroutine(res):
                            await res
                elif ptype == PUBREL:
                    pid = int.from_bytes(body[:2], "big")
                    self._inbound_qos2.discard(pid)
                    self._writer.write(
                        encode_packet(PUBCOMP, 0, pid.to_bytes(2, "big")))
                    await self._writer.drain()
                elif ptype in (PUBACK, PUBREC, PUBCOMP, SUBACK, UNSUBACK):
                    pid = int.from_bytes(body[:2], "big")
                    fut = self._acks.pop(pid, None)
                    if fut is not None and not fut.done():
                        fut.set_result(body)
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            pass
        finally:
            if not self._closing and self.on_disconnect is not None:
                res = self.on_disconnect()
                if asyncio.iscoroutine(res):
                    try:
                        await res
                    except Exception:   # reconnect failures are the
                        pass            # scheduler's problem, not ours

    async def subscribe(self, topic: str, qos: int = 0) -> None:
        pid = self._next_id()
        fut = asyncio.get_running_loop().create_future()
        self._acks[pid] = fut
        self._writer.write(encode_subscribe(pid, [(topic, qos)]))
        await self._writer.drain()
        await asyncio.wait_for(fut, 10)

    async def publish(self, topic: str, payload: bytes, qos: int = 0) -> None:
        pid = self._next_id() if qos else 0
        if qos:
            fut = asyncio.get_running_loop().create_future()
            self._acks[pid] = fut
        self._writer.write(encode_publish(topic, payload, qos, pid))
        await self._writer.drain()
        if qos == 1:
            await asyncio.wait_for(fut, 10)          # PUBACK
        elif qos == 2:
            await asyncio.wait_for(fut, 10)          # PUBREC
            fut2 = asyncio.get_running_loop().create_future()
            self._acks[pid] = fut2
            self._writer.write(
                encode_packet(PUBREL, 0x02, pid.to_bytes(2, "big")))
            await self._writer.drain()
            await asyncio.wait_for(fut2, 10)         # PUBCOMP

    async def disconnect(self) -> None:
        self._closing = True
        for t in (self._ping_task, self._task):
            if t is not None:
                t.cancel()
        if self._writer is not None:
            try:
                self._writer.write(encode_packet(DISCONNECT, 0, b""))
                await self._writer.drain()
            except ConnectionError:
                pass
            self._writer.close()


class MqttBroker:
    """Embedded MQTT broker (QoS 0/1 fan-out, wildcard subscriptions) — the
    analog of the reference's embedded ActiveMQ broker receiver
    (sources/activemq/ActiveMqBrokerEventReceiver.java), and the test/load
    harness for MQTT paths."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host, self.port = host, port
        self._server: asyncio.AbstractServer | None = None
        # writer -> list of subscription patterns
        self._subs: dict[asyncio.StreamWriter, list[str]] = {}

    @property
    def bound_port(self) -> int:
        assert self._server is not None and self._server.sockets
        return self._server.sockets[0].getsockname()[1]

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)

    async def stop(self) -> None:
        # close live client connections BEFORE wait_closed(): in Python 3.12
        # Server.wait_closed() blocks until every connection handler returns
        for w in list(self._subs):
            w.close()
        self._subs.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            ptype, _, _ = await read_packet(reader)
            if ptype != CONNECT:
                writer.close()
                return
            writer.write(encode_packet(CONNACK, 0, b"\x00\x00"))
            await writer.drain()
            self._subs[writer] = []
            # per-connection exactly-once inbox: PUBLISH(qos2) parks here
            # until its PUBREL; redeliveries with the same pid overwrite
            # (never fan out twice)
            pending_qos2: dict[int, tuple[str, bytes]] = {}
            while True:
                ptype, flags, body = await read_packet(reader)
                if ptype == PUBLISH:
                    topic, payload, qos, pid = decode_publish(flags, body)
                    if qos == 1:
                        writer.write(encode_packet(PUBACK, 0, pid.to_bytes(2, "big")))
                        await writer.drain()
                        await self._fanout(topic, payload)
                    elif qos == 2:
                        pending_qos2[pid] = (topic, payload)
                        writer.write(encode_packet(PUBREC, 0, pid.to_bytes(2, "big")))
                        await writer.drain()
                    else:
                        await self._fanout(topic, payload)
                elif ptype == PUBREL:
                    pid = int.from_bytes(body[:2], "big")
                    parked = pending_qos2.pop(pid, None)
                    writer.write(encode_packet(PUBCOMP, 0, pid.to_bytes(2, "big")))
                    await writer.drain()
                    if parked is not None:
                        await self._fanout(*parked)
                elif ptype == SUBSCRIBE:
                    pid = int.from_bytes(body[:2], "big")
                    off, grants = 2, []
                    while off < len(body):
                        tlen = int.from_bytes(body[off: off + 2], "big")
                        topic = body[off + 2: off + 2 + tlen].decode()
                        qos = body[off + 2 + tlen]
                        off += 3 + tlen
                        self._subs[writer].append(topic)
                        grants.append(min(qos, 2))
                    writer.write(
                        encode_packet(SUBACK, 0, pid.to_bytes(2, "big") + bytes(grants))
                    )
                    await writer.drain()
                elif ptype == PINGREQ:
                    writer.write(encode_packet(PINGRESP, 0, b""))
                    await writer.drain()
                elif ptype == DISCONNECT:
                    break
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            self._subs.pop(writer, None)
            writer.close()

    async def _fanout(self, topic: str, payload: bytes) -> None:
        pkt = encode_publish(topic, payload, 0, 0)
        for w, patterns in list(self._subs.items()):
            if any(topic_matches(p, topic) for p in patterns):
                try:
                    w.write(pkt)
                    await w.drain()
                except ConnectionError:
                    self._subs.pop(w, None)


class MqttEventReceiver(InboundEventReceiver):
    """Subscribe to a broker topic and submit payloads to the event source
    (reference: sources/mqtt/MqttInboundEventReceiver.java). A dropped
    connection schedules reconnect attempts with exponential backoff and
    re-subscribes — the reference receiver's scheduled-reconnect behavior."""

    def __init__(self, host: str, port: int, topic: str = "sitewhere/input/#",
                 qos: int = 0, client_id: str = "sw-ingest",
                 username: str | None = None, password: str | None = None,
                 reconnect_initial_s: float = 0.2,
                 reconnect_max_s: float = 30.0):
        super().__init__(f"mqtt:{topic}")
        self.topic, self.qos = topic, qos
        self.client = MqttClient(host, port, client_id, username, password)
        self.reconnect_initial_s = reconnect_initial_s
        self.reconnect_max_s = reconnect_max_s
        self.reconnects = 0            # successful re-connections (metrics)
        self._stopping = False
        self._reconnect_task: asyncio.Task | None = None

    async def on_start(self) -> None:
        self.client.on_message = lambda topic, payload: self.submit(
            payload, {"topic": topic}
        )
        self.client.on_disconnect = self._schedule_reconnect
        await self.client.connect()
        await self.client.subscribe(self.topic, self.qos)

    def _schedule_reconnect(self) -> None:
        if self._stopping or (
            self._reconnect_task is not None and not self._reconnect_task.done()
        ):
            return
        self._reconnect_task = asyncio.get_running_loop().create_task(
            self._reconnect_loop())

    async def _reconnect_loop(self) -> None:
        delay = self.reconnect_initial_s
        while not self._stopping:
            await asyncio.sleep(delay)
            try:
                await self.client.connect()
                await self.client.subscribe(self.topic, self.qos)
                self.reconnects += 1
                logger.info("mqtt receiver %s reconnected", self.name)
                return
            except asyncio.CancelledError:
                raise
            except Exception:
                # any handshake failure (refused, half-open CONNACK ->
                # IncompleteReadError/IndexError, timeout) just backs off;
                # a dead reconnect loop would strand the receiver forever
                delay = min(delay * 2, self.reconnect_max_s)

    async def on_stop(self) -> None:
        self._stopping = True
        if self._reconnect_task is not None:
            self._reconnect_task.cancel()
        await self.client.disconnect()
