"""Persistent-connection wire edge: socket frames straight into staging arenas.

The reference fronts MQTT and broker-style sources as its primary ingest
protocols (SURVEY.md §2.1), but until this module the TPU build's edge was
request-response: every telemetry round-trip paid HTTP framing and the
seed-era ``ingest/sources.py`` receivers handed payloads to the engine one
``submit()`` at a time — one decode, one engine-lock acquisition, one
``process()`` per event, bypassing the zero-copy arena machinery entirely.

This module is the batched persistent-connection edge:

* ``WireBatcher`` — the shared batched-submit accumulator. Frames from any
  number of connections append under one lock; an adaptive flush (size OR
  deadline, whichever first) drains the arrival window into ONE
  ``engine.ingest_json_batch`` / ``ingest_binary_batch`` call per
  (tenant, wire-format) run. The engine's native scanner decodes the
  payload list straight into a pooled ``StagingArena`` (the PR-2/PR-4
  path; the PR-17 slot-routed scatter when the engine is an
  ``SpmdEngine`` — the batcher calls the same inherited facade), so the
  edge adds **zero per-frame host copies**: payload bytes are held by
  reference from socket read to arena scan.
* ``WireEdge`` — asyncio listeners speaking MQTT 3.1.1 (server side of
  ingest/mqtt.py's codec), a length-prefixed binary/JSON TCP protocol
  ("SWP"), and optionally websocket frames, all feeding per-connection-shard
  ``WireBatcher`` instances.

Durability and backpressure contracts (the part that must not be wrong):

* **WAL-before-ack.** An MQTT PUBACK/PUBCOMP or SWP cumulative ack is
  released only after the frame's batch has passed the WAL durability
  watermark (``IngestLog.wait_durable`` on the batch's append ticket —
  the same fsync-before-dispatch gate the engine uses; that discipline is
  unchanged). A client that saw an ack can never lose that frame to a
  crash; a frame lost to a crash was never acked, and MQTT QoS 1
  redelivery (DUP) re-offers it.
* **Admission at the edge, never inside the engine** (PR-9 rule). Each
  arriving frame consults ``utils/qos.admit_or_raise`` — the SAME shared
  admission helper the REST/RPC edges use — before touching the batcher.
  A ``ShedError`` maps to protocol-native backpressure: MQTT withholds the
  PUBACK (and optionally disconnects, so the client's redelivery backs
  off); SWP sends an explicit shed code with a Retry-After; websocket
  mirrors SWP. Replay/standby paths never pass through here, so durable
  events can never be shed (the engine-side invariant is preserved).
* **At-most-once per (tenant, deviceToken, alternateId) across
  redeliveries.** QoS 1 redelivery (PUBACK lost in transit) must not
  double-ingest. The edge keeps a bounded ring over the dedup triples of
  STAGED frames (byte-scan extraction, no JSON decode — the zero-copy
  claim holds), keyed exactly like ``AlternateIdDeduplicator`` so
  tenants/devices reusing an alternateId stay distinct. The ring commits
  only at staging (``on_staged``), never at admission: a frame that
  sheds or stalls after admission leaves no ring entry, so its
  redelivery is re-admitted rather than acked as a duplicate of an
  ingest that never happened (the ack-without-ingest hole). A true
  duplicate is not re-ingested, and its ack rides the next durability
  point (the original is durable by then or will be with it).

Conservation terms (utils/conservation.py "wire" stage): every frame gets
exactly one edge disposition —

    frames_received == frames_admitted + frames_shed
                       + frames_invalid + frames_duplicate
    frames_admitted == rows_submitted + frames_stalled + pending

``rows_submitted`` then flows into the existing staged-rows equation via the
ordinary batch-ingest path. All series scrape as ``swtpu_wire_*`` and are
deliberately NOT ``engine.metrics()`` keys (dispatch-shape equality pin).

SWP framing contract (documented for client implementors):

    client -> server   handshake line  b"SWTP1 <tenant> <json|binary>\\n"
    client -> server   frames          [u32 BE length][payload]
                       length 0 = flush hint (ack pending frames promptly)
    server -> client   0x06 [u32 BE n]  cumulative ack: n admitted frames
                                        from this connection are DURABLE
    server -> client   0x15 [u32 BE retry_after_ms]  frame shed, resend
    server -> client   0x19 [u32 BE max_frame_bytes] protocol error /
                                        oversized frame; connection closes
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import logging
import struct
import threading
import time
from typing import Any, Callable

from sitewhere_tpu.utils.qos import ShedError, admit_or_raise

logger = logging.getLogger(__name__)

# SWP (SiteWhere-TPU wire protocol) server->client codes
SWP_MAGIC = b"SWTP1"
SWP_ACK = 0x06          # cumulative durable-frame ack
SWP_SHED = 0x15         # admission shed / arena stall: resend after delay
SWP_ERR = 0x19          # protocol error or oversized frame; closing


def _scan_string_field(payload: bytes, key: bytes) -> str | None:
    """Best-effort string-field extraction from a raw JSON payload via a
    byte scan — no decode, no copy of the payload. Returns None when the key
    is absent or anything about the value looks unusual (ambiguity must
    never block ingest; the engine-side decode is the arbiter)."""
    idx = payload.find(key)
    if idx < 0:
        return None
    i = idx + len(key)
    n = len(payload)
    while i < n and payload[i] in b" \t\r\n":
        i += 1
    if i >= n or payload[i] != 0x3A:          # ':'
        return None
    i += 1
    while i < n and payload[i] in b" \t\r\n":
        i += 1
    if i >= n or payload[i] != 0x22:          # '"'
        return None
    i += 1
    out = bytearray()
    while i < n:
        b = payload[i]
        if b == 0x5C:                          # backslash escape
            if i + 1 >= n:
                return None
            out.append(payload[i + 1])
            i += 2
            continue
        if b == 0x22:
            try:
                return out.decode()
            except UnicodeDecodeError:
                return None
        out.append(b)
        i += 1
    return None


def extract_alternate_id(payload: bytes) -> str | None:
    return _scan_string_field(payload, b'"alternateId"')


def extract_device_token(payload: bytes) -> str | None:
    return _scan_string_field(payload, b'"deviceToken"')


class AltIdRing:
    """Bounded FIFO membership ring over the dedup keys of STAGED frames —
    ``(tenant, device_token, alternate_id)``, the same triple
    ingest/dedup.AlternateIdDeduplicator uses, byte-scanned rather than
    built from a DecodedRequest. Keys enter the ring only once their frame
    has actually staged (``on_staged``), never at admission: a frame that
    sheds or stalls after admission left no trace here, so its redelivery
    is admitted like a first offer instead of being acked as a duplicate
    of an ingest that never happened.

    Thread-safe: ``seen`` runs on the event-loop thread, ``add`` on the
    batcher's flusher thread."""

    def __init__(self, capacity: int = 65536):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._seen: set = set()
        self._order: collections.deque = collections.deque()

    def seen(self, key) -> bool:
        with self._lock:
            return key in self._seen

    def add(self, key) -> None:
        with self._lock:
            if key in self._seen:
                return
            self._seen.add(key)
            self._order.append(key)
            while len(self._order) > self.capacity:
                self._seen.discard(self._order.popleft())


class WireBatcher:
    """Arrival-window frame accumulator -> batched arena submission.

    Thread-safe: connection handlers (event-loop thread) append frames;
    a dedicated flusher thread drains the window into the engine whenever
    the size threshold is reached OR the oldest frame's deadline expires —
    whichever first. The engine call happens OFF the socket loop, so a
    slow dispatch never stalls frame reception; backpressure is the arena
    pool's own recycle gate (surfaced as ``ShedError`` -> per-frame
    ``on_stall``).

    Also the batched-submit API ``ingest/sources.py`` routes through
    (satellite: CoAP/polling/in-memory receivers stop paying one
    engine-lock acquisition per event).
    """

    def __init__(self, engine, flush_rows: int = 256,
                 flush_interval_s: float = 0.005, auto: bool = True,
                 clock: Callable[[], float] = time.monotonic):
        self.engine = engine
        self.flush_rows = max(1, int(flush_rows))
        self.flush_interval_s = float(flush_interval_s)
        self._clock = clock
        self._cond = threading.Condition(threading.Lock())
        # pending: (payload, tenant, binary, on_durable, on_stall,
        # on_staged).
        # A deque because the intake fast path appends WITHOUT the
        # condition lock: deque.append is a single atomic op under the
        # GIL, and the flusher drains by popleft-until-empty, so a frame
        # appended mid-drain is either included or left for the next
        # window — never lost. Only the window-arming frame (which must
        # stamp the deadline and wake the flusher) and frames at/past
        # the size threshold take the lock; frames 2..N-1 of a window
        # pay one append + one length check.
        self._pending: collections.deque[tuple] = collections.deque()
        self._armed = False          # an open window's deadline is armed
        self._barriers: list[Callable[[], None]] = []
        self._first_arrival: float | None = None
        self._closed = False
        # counters (all guarded by _cond)
        self.rows_submitted = 0
        self.frames_stalled = 0
        self.flushes_size = 0
        self.flushes_deadline = 0
        self.flushes_drain = 0
        self.flush_rows_sum = 0
        # one submit at a time: keeps ack release ordered with ingest order
        self._submit_lock = threading.Lock()
        self._thread: threading.Thread | None = None
        if auto:
            self._thread = threading.Thread(
                target=self._run, name="swtpu-wire-flush", daemon=True)
            self._thread.start()

    # ------------------------------------------------------------- intake
    def add(self, payload: bytes, tenant: str = "default",
            binary: bool = False,
            on_durable: Callable[[], None] | None = None,
            on_stall: Callable[[ShedError], None] | None = None,
            on_staged: Callable[[], None] | None = None) -> None:
        """Append one admitted frame to the current arrival window.

        ``on_staged`` fires (flusher thread) the moment the frame's run
        has successfully entered the engine — before the durability wait,
        never on a shed/stalled run. It is the dedup-ring commit point:
        ids recorded here belong to frames that really were ingested.

        Lock-free fast path: the deque append is atomic under the GIL,
        so mid-window frames never touch the condition lock. Only the
        window-arming frame (stamps the deadline, wakes the flusher) and
        frames at/past the size threshold take it. The flusher clears
        ``_armed`` under the lock BEFORE re-checking the deque in its
        wait loop, so a frame whose adder observes the stale armed flag
        is always seen by that re-check — no lost wakeup.
        """
        if self._closed:
            raise RuntimeError("wire batcher closed")
        q = self._pending
        q.append((payload, tenant, binary, on_durable, on_stall, on_staged))
        if not self._armed or len(q) >= self.flush_rows:
            with self._cond:
                if not self._armed:
                    self._armed = True
                    self._first_arrival = self._clock()
                self._cond.notify_all()

    def add_barrier(self, callback: Callable[[], None]) -> None:
        """Fire ``callback`` after the next durability point — the ack hook
        for duplicate frames that must not re-ingest but whose sender still
        needs its (lost) ack re-sent."""
        with self._cond:
            if self._closed:
                raise RuntimeError("wire batcher closed")
            if not self._armed:
                self._armed = True
                self._first_arrival = self._clock()
            self._barriers.append(callback)
            self._cond.notify_all()

    @property
    def pending(self) -> int:
        return len(self._pending)

    # -------------------------------------------------------------- flush
    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._closed:
                    if self._pending or self._barriers:
                        if len(self._pending) >= self.flush_rows:
                            self.flushes_size += 1
                            break
                        fa = self._first_arrival
                        if fa is None:
                            # adder raced past its arming step; treat the
                            # window as opening now
                            fa = self._first_arrival = self._clock()
                            self._armed = True
                        remaining = fa + self.flush_interval_s - self._clock()
                        if remaining <= 0:
                            self.flushes_deadline += 1
                            break
                        self._cond.wait(remaining)
                    else:
                        # disarm, then re-check: a frame whose adder saw a
                        # stale armed flag (and skipped the notify) is
                        # caught here; any frame appended after this
                        # disarm sees armed == False and notifies
                        self._armed = False
                        self._first_arrival = None
                        if self._pending or self._barriers:
                            continue
                        self._cond.wait()
                else:
                    return
            self._flush_once()

    def flush(self) -> int:
        """Synchronous drain (shutdown, tests, explicit checkpoints).
        Returns frames submitted by THIS call."""
        with self._cond:
            if self._pending or self._barriers:
                self.flushes_drain += 1
        return self._flush_once()

    def _flush_once(self) -> int:
        with self._submit_lock:
            with self._cond:
                # disarm FIRST, then drain by popleft: an adder appending
                # concurrently either lands in this batch or re-arms and
                # gets the next window
                self._armed = False
                self._first_arrival = None
                barriers, self._barriers = self._barriers, []
            batch: list[tuple] = []
            q = self._pending
            while True:
                try:
                    batch.append(q.popleft())
                except IndexError:
                    break
            if not batch and not barriers:
                return 0
            staged = self._submit(batch)
            self._wait_durable()
            # acks ONLY for frames whose run actually staged — stalled
            # frames keep their acks withheld so the senders redeliver
            for _, _, _, on_durable, _, _ in staged:
                if on_durable is not None:
                    self._safe_cb(on_durable)
            for cb in barriers:
                self._safe_cb(cb)
            return len(staged)

    def _submit(self, batch: list[tuple]) -> list[tuple]:
        """One engine call per (tenant, wire-format) run, preserving frame
        arrival order (per-connection ordering is a store-parity
        requirement). The payload list is handed to the batch-ingest facade
        by reference — the native scanner fills the staging arena straight
        from these buffers (zero per-frame host copies). Returns the
        frames that staged (their acks may be released)."""
        staged: list[tuple] = []
        i = 0
        while i < len(batch):
            j = i
            tenant, binary = batch[i][1], batch[i][2]
            while (j < len(batch) and batch[j][1] == tenant
                   and batch[j][2] == binary):
                j += 1
            run = batch[i:j]
            payloads = [f[0] for f in run]
            try:
                if binary:
                    self.engine.ingest_binary_batch(payloads, tenant=tenant)
                else:
                    self.engine.ingest_json_batch(payloads, tenant=tenant)
                staged.extend(run)
                with self._cond:
                    self.rows_submitted += len(run)
                    self.flush_rows_sum += len(run)
                # staged hooks fire only now: a frame that sheds/stalls
                # above never reaches them (dedup-ring commit point)
                for f in run:
                    if f[5] is not None:
                        self._safe_cb(f[5])
            except ShedError as e:
                # arena-stall shed surfaced by the ingest path; the frames
                # were never staged — withhold their acks so the senders
                # redeliver, and tell SWP clients explicitly
                with self._cond:
                    self.frames_stalled += len(run)
                for f in run:
                    if f[4] is not None:
                        self._safe_cb(lambda cb=f[4]: cb(e))
            except Exception:
                logger.exception("wire batch submit failed "
                                 "(%d frames, tenant=%s)", len(run), tenant)
                with self._cond:
                    self.frames_stalled += len(run)
            i = j
        return staged

    def _wait_durable(self) -> None:
        """WAL-before-ack: gate ack release on the newest append ticket.
        The ticket is read AFTER our appends (happens-before via the engine
        lock inside the batch call), so it covers every frame this flush
        submitted; waiting on a later concurrent ticket is merely
        conservative. No-op without a WAL or with inline (non-group) commit
        — the inline path flushes synchronously on append."""
        wal = getattr(self.engine, "wal", None)
        if wal is None:
            return
        try:
            wal.wait_durable(getattr(self.engine, "_wal_last_seq", 0))
        except Exception:
            # a poisoned WAL means NOTHING further may be acked; frames
            # stay unacked (clients redeliver elsewhere/later) and the
            # engine's own dispatch gate raises loudly on its next batch
            logger.exception("wire ack durability gate failed")

    @staticmethod
    def _safe_cb(cb: Callable) -> None:
        try:
            cb()
        except Exception:
            logger.exception("wire ack callback failed")

    def close(self) -> None:
        """Final drain, then stop the flusher thread."""
        self.flush()
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def counters(self) -> dict[str, int]:
        with self._cond:
            pending = len(self._pending)
            flushes = (self.flushes_size + self.flushes_deadline
                       + self.flushes_drain)
            return {
                "rows_submitted": self.rows_submitted,
                "frames_stalled": self.frames_stalled,
                "pending": pending,
                "flushes_size": self.flushes_size,
                "flushes_deadline": self.flushes_deadline,
                "flushes_drain": self.flushes_drain,
                "flushes": flushes,
                "flush_rows_sum": self.flush_rows_sum,
            }


@dataclasses.dataclass(frozen=True)
class WireEdgeConfig:
    """Operator knobs for one wire edge (see README "Persistent-connection
    wire edge" for the full contract)."""

    host: str = "127.0.0.1"
    mqtt_port: int | None = 0        # 0 = ephemeral; None = listener off
    tcp_port: int | None = None      # SWP length-prefixed listener
    ws_port: int | None = None       # websocket listener (needs websockets)
    flush_rows: int = 256            # arrival-window size threshold
    flush_interval_s: float = 0.005  # arrival-window deadline
    n_shards: int = 1                # connection shards (one batcher each)
    max_frame_bytes: int = 1 << 20   # oversized-frame rejection
    keepalive_grace: float = 1.5     # disconnect after grace * keepalive
    handshake_timeout_s: float = 10.0
    idle_timeout_s: float = 300.0    # SWP/ws idle disconnect
    tenant_in_topic: bool = True     # MQTT topic swtpu/<tenant>/... routing
    default_tenant: str = "default"
    shed_disconnect: bool = True     # drop MQTT conn on shed (backs off
                                     # the client's redelivery loop)
    dedup_capacity: int = 65536      # alternate-id ring per edge


class _Conn:
    """Per-connection state shared by the protocol handlers."""

    __slots__ = ("writer", "proto", "tenant", "binary", "shard",
                 "frames_in", "acked", "_ack_dirty", "qos2_parked",
                 "qos2_inflight", "alive")

    def __init__(self, writer, proto: str, shard: int):
        self.writer = writer
        self.proto = proto
        self.tenant = "default"
        self.binary = False
        self.shard = shard
        self.frames_in = 0
        self.acked = 0              # SWP cumulative durable ack counter
        self._ack_dirty = False
        self.qos2_parked: dict[int, tuple[str, bytes]] = {}
        # pids released by PUBREL whose ingest outcome is still pending
        # (staging, or shed awaiting re-park) — a retransmitted PUBREL
        # for one of these must NOT be treated as a completed duplicate
        self.qos2_inflight: set[int] = set()
        self.alive = True


class WireEdge:
    """Persistent-connection ingest edge bound to one engine.

    ``await edge.start()`` inside a running event loop; connections shard
    round-robin onto ``n_shards`` :class:`WireBatcher` accumulators. The
    edge registers itself on ``engine.wire_edges`` so the conservation
    ledger and the ``swtpu_wire_*`` scrape exporter can find it."""

    def __init__(self, engine, config: WireEdgeConfig | None = None):
        self.engine = engine
        self.cfg = config or WireEdgeConfig()
        self.batchers = [
            WireBatcher(engine, flush_rows=self.cfg.flush_rows,
                        flush_interval_s=self.cfg.flush_interval_s)
            for _ in range(max(1, self.cfg.n_shards))
        ]
        self._lock = threading.Lock()
        self._conns: set[_Conn] = set()
        self._servers: list = []
        self._ws_server = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._next_shard = 0
        self._dedup = AltIdRing(self.cfg.dedup_capacity)
        # edge-disposition counters (conservation "wire" stage; _lock)
        self.frames_received = 0
        self.frames_admitted = 0
        self.frames_shed = 0
        self.frames_invalid = 0
        self.frames_duplicate = 0
        self.backpressure_events = 0
        self.keepalive_timeouts = 0
        self.connections_opened = 0
        self.connections_peak = 0

    # ---------------------------------------------------------- lifecycle
    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        if self.cfg.mqtt_port is not None:
            srv = await asyncio.start_server(
                self._handle_mqtt, self.cfg.host, self.cfg.mqtt_port)
            self._servers.append(srv)
        if self.cfg.tcp_port is not None:
            srv = await asyncio.start_server(
                self._handle_swp, self.cfg.host, self.cfg.tcp_port)
            self._servers.append(srv)
        if self.cfg.ws_port is not None:
            try:
                import websockets
            except ImportError:
                logger.warning("websocket listener disabled: websockets "
                               "library unavailable")
            else:
                self._ws_server = await websockets.serve(
                    self._handle_ws, self.cfg.host, self.cfg.ws_port)
        edges = getattr(self.engine, "wire_edges", None)
        if edges is None:
            edges = self.engine.wire_edges = []
        edges.append(self)

    async def stop(self) -> None:
        for srv in self._servers:
            srv.close()
            await srv.wait_closed()
        self._servers.clear()
        if self._ws_server is not None:
            self._ws_server.close()
            await self._ws_server.wait_closed()
            self._ws_server = None
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            conn.alive = False
            try:
                conn.writer.close()
            except Exception:
                pass
        # final drain so every admitted frame reaches the engine (and its
        # ack, if the connection is still up, goes out before teardown)
        for b in self.batchers:
            await asyncio.get_running_loop().run_in_executor(None, b.close)
        edges = getattr(self.engine, "wire_edges", None)
        if edges and self in edges:
            edges.remove(self)

    def kill(self) -> None:
        """Abrupt teardown for crash drills: close sockets, do NOT drain
        batchers — pending (unacked) frames are dropped exactly as a
        process crash would drop them. Acked frames are already durable."""
        for srv in self._servers:
            srv.close()
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            conn.alive = False
            try:
                conn.writer.close()
            except Exception:
                pass
        edges = getattr(self.engine, "wire_edges", None)
        if edges and self in edges:
            edges.remove(self)

    # ------------------------------------------------------------- ports
    def _port_of(self, index: int) -> int:
        srv = self._servers[index]
        return srv.sockets[0].getsockname()[1]

    @property
    def mqtt_port(self) -> int:
        assert self.cfg.mqtt_port is not None
        return self._port_of(0)

    @property
    def tcp_port(self) -> int:
        assert self.cfg.tcp_port is not None
        return self._port_of(1 if self.cfg.mqtt_port is not None else 0)

    @property
    def ws_port(self) -> int:
        assert self._ws_server is not None
        return self._ws_server.sockets[0].getsockname()[1]

    # ------------------------------------------------------- registration
    def _register(self, writer, proto: str) -> _Conn:
        with self._lock:
            shard = self._next_shard % len(self.batchers)
            self._next_shard += 1
            conn = _Conn(writer, proto, shard)
            self._conns.add(conn)
            self.connections_opened += 1
            self.connections_peak = max(self.connections_peak,
                                        len(self._conns))
        return conn

    def _unregister(self, conn: _Conn) -> None:
        conn.alive = False
        with self._lock:
            self._conns.discard(conn)

    # ------------------------------------------------------ frame intake
    def _on_frame(self, conn: _Conn, payload: bytes, tenant: str,
                  binary: bool,
                  on_durable: Callable[[], None] | None,
                  on_shed: Callable[[ShedError], None] | None) -> None:
        """One frame's edge disposition: exactly one of admitted / shed /
        duplicate (invalid frames are counted by the framing layer and
        never reach here). Runs on the event-loop thread; everything here
        is O(1) bookkeeping — the engine work happens on the flusher."""
        with self._lock:
            self.frames_received += 1
            conn.frames_in += 1
        alt = extract_alternate_id(payload) if not binary else None
        dedup_key = None
        if alt is not None:
            # the repo's established dedup triple (AlternateIdDeduplicator):
            # two tenants/devices reusing the same alternateId are distinct
            dedup_key = (tenant, extract_device_token(payload) or "", alt)
        if dedup_key is not None and self._dedup.seen(dedup_key):
            with self._lock:
                self.frames_duplicate += 1
            # the key is in the ring only if the original frame STAGED, so
            # re-ack at the next durability point: that point covers the
            # original, and the sender's lost ack is regenerated without a
            # second ingest
            if on_durable is not None:
                self.batchers[conn.shard].add_barrier(on_durable)
            return
        try:
            admit_or_raise(self.engine, tenant, 1)
        except ShedError as e:
            with self._lock:
                self.frames_shed += 1
                self.backpressure_events += 1
            if on_shed is not None:
                on_shed(e)
            return
        with self._lock:
            self.frames_admitted += 1
        # the dedup key commits only when the frame stages (flusher
        # thread): a shed/stalled run leaves no ring entry, so the
        # client's redelivery is re-admitted instead of being acked as
        # a duplicate of an ingest that never happened
        on_staged = None
        if dedup_key is not None:
            on_staged = (lambda ring=self._dedup, k=dedup_key: ring.add(k))
        self.batchers[conn.shard].add(payload, tenant, binary,
                                      on_durable=on_durable,
                                      on_stall=self._stall_cb(conn, on_shed),
                                      on_staged=on_staged)

    def _stall_cb(self, conn: _Conn, on_shed):
        if on_shed is None:
            return None

        def cb(err: ShedError) -> None:
            with self._lock:
                self.backpressure_events += 1
            on_shed(err)
        return cb

    def _count_invalid(self) -> None:
        # invalid frames never reach _on_frame, so they get BOTH their
        # received and invalid increments here — every frame the edge saw
        # has exactly one disposition and the wire-frames conservation
        # equation balances even when malformed traffic arrives
        with self._lock:
            self.frames_received += 1
            self.frames_invalid += 1

    def _call_on_loop(self, fn: Callable[[], None]) -> Callable[[], None]:
        """Wrap a writer-touching callback so the flusher thread hands it
        to the event loop (StreamWriter is not thread-safe)."""
        loop = self._loop

        def cb() -> None:
            try:
                loop.call_soon_threadsafe(fn)
            except RuntimeError:
                # loop already closed (post-kill drain): the socket this
                # ack was headed for is gone — drop it silently
                pass
        return cb

    # ------------------------------------------------------- MQTT server
    def _mqtt_tenant(self, topic: str) -> str:
        if self.cfg.tenant_in_topic:
            parts = topic.split("/")
            if len(parts) >= 2 and parts[0] == "swtpu":
                return parts[1]
        return self.cfg.default_tenant

    async def _handle_mqtt(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        from sitewhere_tpu.ingest.mqtt import (
            CONNACK, CONNECT, DISCONNECT, FrameTooLarge, PINGREQ, PINGRESP,
            PUBACK, PUBCOMP, PUBLISH, PUBREC, PUBREL, SUBACK, SUBSCRIBE,
            UNSUBACK, UNSUBSCRIBE, decode_connect, decode_publish,
            encode_packet, read_packet_limited)

        conn = self._register(writer, "mqtt")
        keepalive = 0
        try:
            ptype, _, body = await asyncio.wait_for(
                read_packet_limited(reader, self.cfg.max_frame_bytes),
                self.cfg.handshake_timeout_s)
            if ptype != CONNECT:
                self._count_invalid()
                return
            _client_id, keepalive = decode_connect(body)
            writer.write(encode_packet(CONNACK, 0, b"\x00\x00"))
            await writer.drain()
            timeout = (keepalive * self.cfg.keepalive_grace
                       if keepalive else None)
            while True:
                try:
                    ptype, flags, body = await asyncio.wait_for(
                        read_packet_limited(reader,
                                            self.cfg.max_frame_bytes),
                        timeout)
                except asyncio.TimeoutError:
                    # keepalive contract (MQTT 3.1.1 [MQTT-3.1.2-24]):
                    # silence past 1.5x the negotiated keepalive means the
                    # client is gone — close so its session can redeliver
                    with self._lock:
                        self.keepalive_timeouts += 1
                    return
                if ptype == PUBLISH:
                    topic, payload, qos, pid = decode_publish(flags, body)
                    tenant = self._mqtt_tenant(topic)
                    self._mqtt_frame(conn, writer, payload, tenant, qos, pid)
                elif ptype == PUBREL:
                    pid = int.from_bytes(body[:2], "big")
                    parked = conn.qos2_parked.pop(pid, None)
                    if parked is not None:
                        self._qos2_release(conn, writer, pid, parked)
                    elif pid in conn.qos2_inflight:
                        # outcome pending (staging, or shed racing its
                        # re-park): neither PUBCOMP nor a second ingest —
                        # the client's next PUBREL retransmission sees
                        # the settled state
                        pass
                    else:
                        # true duplicate PUBREL (the frame completed and
                        # its PUBCOMP was lost): just re-complete
                        self._mqtt_ack(conn, writer, PUBCOMP, pid)()
                elif ptype == PINGREQ:
                    writer.write(encode_packet(PINGRESP, 0, b""))
                    await writer.drain()
                elif ptype == SUBSCRIBE:
                    pid = body[:2]
                    n_topics = max(1, body[2:].count(b"\x00") // 2)
                    writer.write(encode_packet(SUBACK, 0,
                                               pid + b"\x00" * n_topics))
                    await writer.drain()
                elif ptype == UNSUBSCRIBE:
                    writer.write(encode_packet(UNSUBACK, 0, body[:2]))
                    await writer.drain()
                elif ptype == DISCONNECT:
                    return
        except FrameTooLarge:
            self._count_invalid()
        except (asyncio.IncompleteReadError, ConnectionError, ValueError):
            pass
        finally:
            self._unregister(conn)
            try:
                writer.close()
            except Exception:
                pass

    def _mqtt_frame(self, conn: _Conn, writer, payload: bytes, tenant: str,
                    qos: int, pid: int) -> None:
        from sitewhere_tpu.ingest.mqtt import PUBACK, PUBREC, encode_packet

        if qos == 2:
            # exactly-once first half: park until PUBREL releases it. A
            # redelivered PUBLISH with the same pid replaces the parked
            # copy — never a second ingest.
            conn.qos2_parked[pid] = (tenant, payload)
            writer.write(encode_packet(PUBREC, 0, pid.to_bytes(2, "big")))
            return
        on_durable = None
        if qos == 1:
            on_durable = self._call_on_loop(
                self._mqtt_ack(conn, writer, PUBACK, pid))
        self._on_frame(conn, payload, tenant, binary=False,
                       on_durable=on_durable,
                       on_shed=self._mqtt_shed(conn, writer))

    def _qos2_release(self, conn: _Conn, writer, pid: int,
                      parked: tuple[str, bytes]) -> None:
        """Exactly-once second half: a PUBREL released the parked frame.
        The pid is tracked in ``qos2_inflight`` until its outcome settles:

        * staged + durable -> PUBCOMP, pid forgotten (later PUBRELs are
          true duplicates and just re-complete);
        * shed at admission or arena stall -> PUBCOMP withheld and the
          payload goes BACK to the parked map, so the client's PUBREL
          retransmission re-releases it through admission. A PUBCOMP can
          therefore never complete a frame that was not ingested.
        """
        from sitewhere_tpu.ingest.mqtt import PUBCOMP

        tenant, payload = parked
        conn.qos2_inflight.add(pid)
        comp = self._mqtt_ack(conn, writer, PUBCOMP, pid)

        def done() -> None:
            conn.qos2_inflight.discard(pid)
            comp()

        def reoffer(err: ShedError) -> None:
            # admission shed runs on the loop thread, arena stall on the
            # flusher thread — marshal so every qos2 map mutation happens
            # on the loop thread (same thread as the PUBREL handler)
            def _repark() -> None:
                conn.qos2_inflight.discard(pid)
                conn.qos2_parked.setdefault(pid, (tenant, payload))
            try:
                self._loop.call_soon_threadsafe(_repark)
            except RuntimeError:
                pass             # loop closed mid-teardown

        self._on_frame(conn, payload, tenant, binary=False,
                       on_durable=self._call_on_loop(done),
                       on_shed=reoffer)

    def _mqtt_ack(self, conn: _Conn, writer, ptype: int, pid: int):
        from sitewhere_tpu.ingest.mqtt import encode_packet

        def send() -> None:
            if not conn.alive:
                return
            try:
                writer.write(encode_packet(ptype, 0, pid.to_bytes(2, "big")))
                conn.acked += 1
            except Exception:
                pass
        return send

    def _mqtt_shed(self, conn: _Conn, writer):
        """MQTT 3.1.1 has no NACK: backpressure = withhold the PUBACK so
        the sender's in-flight window stalls, and (by default) disconnect
        so its redelivery loop backs off before re-offering with DUP."""
        def on_shed(err: ShedError) -> None:
            if self.cfg.shed_disconnect and conn.alive:
                conn.alive = False
                loop = self._loop

                def _close():
                    try:
                        writer.close()
                    except Exception:
                        pass
                loop.call_soon_threadsafe(_close)
        return on_shed

    # -------------------------------------------------------- SWP server
    async def _handle_swp(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        conn = self._register(writer, "swp")
        try:
            line = await asyncio.wait_for(reader.readline(),
                                          self.cfg.handshake_timeout_s)
            parts = line.split()
            if (len(parts) != 3 or parts[0] != SWP_MAGIC
                    or parts[2] not in (b"json", b"binary")):
                self._count_invalid()
                writer.write(self._swp_rec(SWP_ERR, self.cfg.max_frame_bytes))
                await writer.drain()
                return
            conn.tenant = parts[1].decode()
            conn.binary = parts[2] == b"binary"
            while True:
                hdr = await asyncio.wait_for(reader.readexactly(4),
                                             self.cfg.idle_timeout_s)
                (length,) = struct.unpack("!I", hdr)
                if length == 0:
                    # flush hint: drain this connection's shard promptly
                    batcher = self.batchers[conn.shard]
                    self._loop.run_in_executor(None, batcher.flush)
                    continue
                if length > self.cfg.max_frame_bytes:
                    self._count_invalid()
                    writer.write(self._swp_rec(SWP_ERR,
                                               self.cfg.max_frame_bytes))
                    await writer.drain()
                    return
                payload = await reader.readexactly(length)
                self._swp_frame(conn, writer, payload)
        except (asyncio.IncompleteReadError, asyncio.TimeoutError,
                ConnectionError):
            pass
        finally:
            self._unregister(conn)
            try:
                writer.close()
            except Exception:
                pass

    @staticmethod
    def _swp_rec(code: int, value: int) -> bytes:
        return struct.pack("!BI", code, value & 0xFFFFFFFF)

    def _swp_frame(self, conn: _Conn, writer, payload: bytes) -> None:
        def ack() -> None:
            if not conn.alive:
                return
            conn.acked += 1
            try:
                writer.write(self._swp_rec(SWP_ACK, conn.acked))
            except Exception:
                pass

        def shed(err: ShedError) -> None:
            retry_ms = int(max(0.0, err.retry_after_s) * 1000)

            def _send():
                if not conn.alive:
                    return
                try:
                    writer.write(self._swp_rec(SWP_SHED, retry_ms))
                except Exception:
                    pass
            self._loop.call_soon_threadsafe(_send)

        self._on_frame(conn, payload, conn.tenant, binary=conn.binary,
                       on_durable=self._call_on_loop(ack), on_shed=shed)

    # -------------------------------------------------- websocket server
    async def _handle_ws(self, ws) -> None:
        """Websocket frames ride the SWP contract: first message is the
        handshake line, every further message is one frame; acks and shed
        codes come back as binary messages."""
        writer = _WsWriter(ws, self._loop)
        conn = self._register(writer, "ws")
        try:
            first = await asyncio.wait_for(ws.recv(),
                                           self.cfg.handshake_timeout_s)
            if isinstance(first, str):
                first = first.encode()
            parts = first.split()
            if (len(parts) != 3 or parts[0] != SWP_MAGIC
                    or parts[2] not in (b"json", b"binary")):
                self._count_invalid()
                await ws.send(self._swp_rec(SWP_ERR,
                                            self.cfg.max_frame_bytes))
                return
            conn.tenant = parts[1].decode()
            conn.binary = parts[2] == b"binary"
            async for message in ws:
                payload = (message.encode()
                           if isinstance(message, str) else message)
                if len(payload) > self.cfg.max_frame_bytes:
                    self._count_invalid()
                    await ws.send(self._swp_rec(SWP_ERR,
                                                self.cfg.max_frame_bytes))
                    return
                self._swp_frame(conn, writer, payload)
        except Exception:
            pass
        finally:
            self._unregister(conn)

    # ------------------------------------------------------------ reports
    def snapshot(self) -> dict[str, int]:
        """One internally consistent counter snapshot (edge lock), plus the
        shard batchers' totals — the conservation ledger's "wire" stage and
        the ``swtpu_wire_*`` exporter both read exactly this."""
        with self._lock:
            out = {
                "frames_received": self.frames_received,
                "frames_admitted": self.frames_admitted,
                "frames_shed": self.frames_shed,
                "frames_invalid": self.frames_invalid,
                "frames_duplicate": self.frames_duplicate,
                "backpressure_events": self.backpressure_events,
                "keepalive_timeouts": self.keepalive_timeouts,
                "connections_live": len(self._conns),
                "connections_peak": self.connections_peak,
                "connections_opened": self.connections_opened,
            }
        rows = stalled = pending = flushes = rows_sum = 0
        for b in self.batchers:
            c = b.counters()
            rows += c["rows_submitted"]
            stalled += c["frames_stalled"]
            pending += c["pending"]
            flushes += c["flushes"]
            rows_sum += c["flush_rows_sum"]
        out.update({
            "rows_submitted": rows,
            "frames_stalled": stalled,
            "pending": pending,
            "flushes": flushes,
            "flush_rows_sum": rows_sum,
            "flush_occupancy_pct": round(
                100.0 * rows_sum / (flushes * self.cfg.flush_rows), 1)
            if flushes else 0.0,
        })
        return out


class _WsWriter:
    """Duck-typed StreamWriter facade so websocket connections share the
    SWP frame/ack path. ``write`` schedules the async send; ``close``
    schedules the websocket close."""

    def __init__(self, ws, loop):
        self._ws = ws
        self._loop = loop

    def write(self, data: bytes) -> None:
        # only ever called on the event-loop thread (ack callbacks are
        # marshalled there via call_soon_threadsafe)
        asyncio.ensure_future(self._send(bytes(data)))

    async def _send(self, data: bytes) -> None:
        try:
            await self._ws.send(data)
        except Exception:
            pass

    def close(self) -> None:
        asyncio.ensure_future(self._ws.close())


def aggregate_wire_snapshot(engine) -> dict[str, Any] | None:
    """Combine the snapshots of every edge attached to ``engine`` — the
    shape the conservation ledger, the REST status route, and the scrape
    exporter share. None when no edge is (or ever was) attached.

    Counters sum; the two non-additive fields get their own rules:
    ``connections_peak`` is a max (per-edge peaks are not concurrent),
    and ``flush_occupancy_pct`` is recomputed as a flush-capacity-weighted
    mean (total flushed rows over total flush capacity) — summing
    percentages would report 160% for two edges at 80%."""
    edges = getattr(engine, "wire_edges", None)
    if not edges:
        return None
    total: dict[str, Any] = {}
    rows_sum = cap_sum = 0
    for edge in list(edges):
        snap = edge.snapshot()
        rows_sum += snap.get("flush_rows_sum", 0)
        cap_sum += snap.get("flushes", 0) * edge.cfg.flush_rows
        for key, val in snap.items():
            if key == "connections_peak":
                total[key] = max(total.get(key, 0), val)
            elif key == "flush_occupancy_pct":
                continue
            else:
                total[key] = total.get(key, 0) + val
    total["flush_occupancy_pct"] = (
        round(100.0 * rows_sum / cap_sum, 1) if cap_sum else 0.0)
    return total
