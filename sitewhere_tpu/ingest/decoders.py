"""Event decoders: bytes -> DecodedRequest list.

Decoder lineup mirrors the reference's (SURVEY.md §2.1): JSON device-request,
JSON string, JSON batch, binary ("protobuf" slot — here a compact
struct-packed flat format, since our wire schema is flat SoA, not GPB),
scripted (a user Python callable instead of Groovy — same binding contract:
payload + metadata in, requests out), composite (metadata extractor + per-
device-type delegation, sources/decoder/composite/*), and the debug decoders
(echo / payload logger, sources/decoder/debug/*).
"""

from __future__ import annotations

import json
import logging
import struct
from typing import Any, Callable, Protocol

from sitewhere_tpu.core.types import AlertLevel
from sitewhere_tpu.ingest.requests import (
    DecodedRequest,
    EventDecodeException,
    RequestType,
    parse_request_type,
)

logger = logging.getLogger(__name__)


class EventDecoder(Protocol):
    def decode(self, payload: bytes, metadata: dict[str, Any]) -> list[DecodedRequest]:
        ...


def _parse_event_date(req: dict) -> int | None:
    ts = req.get("eventDate")
    if ts is None:
        return None
    if isinstance(ts, (int, float)):
        return int(ts)
    # ISO-8601 strings accepted for REST parity
    import datetime

    try:
        return int(
            datetime.datetime.fromisoformat(str(ts).replace("Z", "+00:00")).timestamp() * 1000
        )
    except ValueError as e:
        raise EventDecodeException(f"bad eventDate: {ts!r}") from e


def request_from_envelope(envelope: dict, metadata: dict | None = None) -> DecodedRequest:
    """Map one DeviceRequest JSON envelope to a DecodedRequest."""
    try:
        rtype = parse_request_type(envelope["type"])
        token = envelope.get("deviceToken") or envelope.get("hardwareId")
        if not token:
            raise EventDecodeException("missing deviceToken")
        req = envelope.get("request", {}) or {}
        out = DecodedRequest(
            type=rtype,
            device_token=str(token),
            tenant=str(envelope.get("tenant", "default")),
            event_ts_ms=_parse_event_date(req),
            alternate_id=req.get("alternateId"),
            metadata=dict(metadata or {}) | dict(req.get("metadata") or {}),
        )
        if rtype is RequestType.DEVICE_MEASUREMENT:
            # JSON null values parse as absent, matching the native decoder
            # (a measurement with a null value still decodes, with no lanes)
            if "measurements" in req and isinstance(req["measurements"], dict):
                out.measurements = {str(k): float(v)
                                    for k, v in req["measurements"].items()
                                    if v is not None}
            elif "name" in req:
                out.measurements = (
                    {str(req["name"]): float(req["value"])}
                    if req.get("value") is not None else {}
                )
            else:
                raise EventDecodeException("measurement request missing name/value")
        elif rtype is RequestType.DEVICE_LOCATION:
            # null coordinates decode as an absent location (native parity:
            # have_loc stays false) — never as null island (0, 0)
            if req["latitude"] is not None and req["longitude"] is not None:
                out.latitude = float(req["latitude"])
                out.longitude = float(req["longitude"])
            out.elevation = float(req.get("elevation") or 0.0)
        elif rtype is RequestType.DEVICE_ALERT:
            out.alert_type = str(req.get("type") or "alert")
            lvl = req.get("level") or "Info"
            out.alert_level = (
                AlertLevel[str(lvl).upper()] if isinstance(lvl, str) else AlertLevel(int(lvl))
            )
            out.alert_message = req.get("message")
        elif rtype is RequestType.ACKNOWLEDGE:
            out.originating_event_id = req.get("originatingEventId")
            out.response = req.get("response")
        elif rtype is RequestType.DEVICE_STATE_CHANGE:
            out.attribute = str(req.get("attribute", ""))
            out.state_type = str(req.get("type", ""))
            out.previous_state = req.get("previousState")
            out.new_state = req.get("newState")
        else:
            out.extras = {k: v for k, v in req.items() if k not in ("metadata",)}
        return out
    except EventDecodeException:
        raise
    except (KeyError, TypeError, ValueError) as e:
        raise EventDecodeException(str(e)) from e


class JsonDeviceRequestDecoder:
    """Parse a single DeviceRequest envelope
    (reference: sources/decoder/json/JsonDeviceRequestDecoder.java)."""

    # raw payloads in this format may skip host-side decode entirely and
    # ride the engine's batched arena path (ingest_json_batch) — the
    # wire-edge batched submit keys off this tag (ingest/wire_edge.py)
    wire_tag = "json"

    def decode(self, payload: bytes, metadata: dict[str, Any]) -> list[DecodedRequest]:
        try:
            envelope = json.loads(payload)
        except json.JSONDecodeError as e:
            raise EventDecodeException(f"invalid JSON: {e}") from e
        if not isinstance(envelope, dict):
            raise EventDecodeException("payload is not a JSON object")
        return [request_from_envelope(envelope, metadata)]


class JsonStringDecoder(JsonDeviceRequestDecoder):
    """String payload variant (reference: JsonStringDeviceRequestDecoder)."""

    def decode(self, payload, metadata):
        if isinstance(payload, str):
            payload = payload.encode()
        return super().decode(payload, metadata)


class JsonBatchEventDecoder:
    """Batch envelope: list of DeviceRequests, or a map with shared token
    (reference: sources/decoder/json/JsonBatchEventDecoder.java)."""

    def decode(self, payload: bytes, metadata: dict[str, Any]) -> list[DecodedRequest]:
        try:
            data = json.loads(payload)
        except json.JSONDecodeError as e:
            raise EventDecodeException(f"invalid JSON: {e}") from e
        if isinstance(data, list):
            return [request_from_envelope(item, metadata) for item in data]
        if isinstance(data, dict) and "requests" in data:
            token = data.get("deviceToken")
            out = []
            for item in data["requests"]:
                if token and "deviceToken" not in item:
                    item = {**item, "deviceToken": token}
                out.append(request_from_envelope(item, metadata))
            return out
        raise EventDecodeException("batch payload must be a list or {requests: []}")


def split_json_array(raw: bytes) -> list[bytes]:
    """Split a top-level JSON array into its raw element byte slices without
    materializing Python objects — the bulk REST ingest path hands the
    slices straight to the native batch decoder (one parse total instead
    of parse + re-serialize + parse)."""
    i, n = 0, len(raw)
    while i < n and raw[i] in b" \t\r\n":
        i += 1
    if i >= n or raw[i] != ord("["):
        raise EventDecodeException("expected a JSON array")
    i += 1
    out: list[bytes] = []
    depth = 0
    in_str = False
    esc = False
    start = -1
    while i < n:
        c = raw[i]
        if in_str:
            if esc:
                esc = False
            elif c == ord("\\"):
                esc = True
            elif c == ord('"'):
                in_str = False
        elif c == ord('"'):
            in_str = True
            if depth == 0 and start < 0:
                start = i
        elif c in b"{[":
            if depth == 0 and start < 0:
                start = i
            depth += 1
        elif c in b"}]":
            if depth == 0 and c == ord("]"):   # end of the top-level array
                if start >= 0:
                    out.append(raw[start:i].strip())
                return out
            depth -= 1
        elif depth == 0:
            if c == ord(","):
                if start < 0:
                    raise EventDecodeException("empty array element")
                out.append(raw[start:i].strip())
                start = -1
            elif start < 0 and c not in b" \t\r\n":
                start = i                       # literal/number element
        i += 1
    raise EventDecodeException("unterminated JSON array")


# --- binary flat format (the "protobuf decoder" slot) ------------------------
#
# Layout (little-endian), versioned; replaces GPB with a schema tuned for
# zero-copy batch packing:
#   u8 version=1 | u8 type | u16 token_len | token utf8 | i64 ts_ms |
#   u16 n_pairs | n_pairs * (u16 name_len | name | f64 value)      (measurement)
#   f64 lat | f64 lon | f64 elev  (NaN = absent coordinate)         (location)
#   u16 type_len | type | u8 level | u16 msg_len | msg              (alert)
#   u16 n_extras | n * (u16 klen | k | u16 vlen | v)  [optional]    (register)
#   u16 orig_len | orig | u16 resp_len | resp         [optional]    (ack)

_BIN_MAGIC_VERSION = 1
_BIN_TYPES = {
    1: RequestType.DEVICE_MEASUREMENT,
    2: RequestType.DEVICE_LOCATION,
    3: RequestType.DEVICE_ALERT,
    4: RequestType.REGISTER_DEVICE,
    5: RequestType.ACKNOWLEDGE,
}
_BIN_TYPE_IDS = {v: k for k, v in _BIN_TYPES.items()}


def encode_binary_request(req: DecodedRequest) -> bytes:
    """Inverse of BinaryEventDecoder (reference: ProtobufDeviceEventEncoder
    slot) — used by tests, the load generator, and socket senders."""
    tid = _BIN_TYPE_IDS[req.type]
    tok = req.device_token.encode()
    out = struct.pack("<BBH", _BIN_MAGIC_VERSION, tid, len(tok)) + tok
    out += struct.pack("<q", req.event_ts_ms if req.event_ts_ms is not None else -1)
    if req.type is RequestType.DEVICE_MEASUREMENT:
        pairs = req.measurements or {}
        out += struct.pack("<H", len(pairs))
        for name, value in pairs.items():
            nb = name.encode()
            out += struct.pack("<H", len(nb)) + nb + struct.pack("<d", float(value))
    elif req.type is RequestType.DEVICE_LOCATION:
        # NaN wires "absent coordinates" so a null-coord location survives a
        # binary round trip without turning into null island (0, 0)
        out += struct.pack(
            "<ddd",
            req.latitude if req.latitude is not None else float("nan"),
            req.longitude if req.longitude is not None else float("nan"),
            req.elevation or 0.0)
    elif req.type is RequestType.DEVICE_ALERT:
        tb = (req.alert_type or "alert").encode()
        mb = (req.alert_message or "").encode()
        out += struct.pack("<H", len(tb)) + tb
        out += struct.pack("<B", int(req.alert_level))
        out += struct.pack("<H", len(mb)) + mb
    elif req.type is RequestType.REGISTER_DEVICE:
        # string extras (deviceTypeToken/areaToken/customerToken) must
        # survive the wire or WAL replay loses registration fidelity
        pairs = [(k, v) for k, v in (req.extras or {}).items()
                 if isinstance(v, str)]
        out += struct.pack("<H", len(pairs))
        for k, v in pairs:
            kb, vb = k.encode(), v.encode()
            out += struct.pack("<H", len(kb)) + kb
            out += struct.pack("<H", len(vb)) + vb
    elif req.type is RequestType.ACKNOWLEDGE:
        ob = (req.originating_event_id or "").encode()
        rb = (req.response or "").encode()
        out += struct.pack("<H", len(ob)) + ob
        out += struct.pack("<H", len(rb)) + rb
    return out


def binary_token_of(payload: bytes) -> str | None:
    """Device token of one binary wire payload WITHOUT a full decode —
    the cluster router's partition key (it needs only the token, like the
    Kafka producer keying on deviceToken)."""
    if len(payload) < 4 or payload[0] != _BIN_MAGIC_VERSION:
        return None
    (n,) = struct.unpack_from("<H", payload, 2)
    tok = payload[4:4 + n]
    if len(tok) != n:
        return None
    try:
        return tok.decode()
    except UnicodeDecodeError:
        return None


def envelope_from_request(req: DecodedRequest) -> dict:
    """Inverse of request_from_envelope: re-serialize a DecodedRequest as
    the DeviceRequest JSON envelope, so single events route across cluster
    ranks on the same wire shape devices send (round-trip tested)."""
    body: dict = {}
    if req.event_ts_ms is not None:
        body["eventDate"] = req.event_ts_ms
    if req.alternate_id is not None:
        body["alternateId"] = req.alternate_id
    if req.metadata:
        body["metadata"] = dict(req.metadata)
    if req.type is RequestType.DEVICE_MEASUREMENT:
        body["measurements"] = dict(req.measurements or {})
    elif req.type is RequestType.DEVICE_LOCATION:
        body["latitude"] = req.latitude
        body["longitude"] = req.longitude
        body["elevation"] = req.elevation
    elif req.type is RequestType.DEVICE_ALERT:
        body["type"] = req.alert_type
        body["level"] = req.alert_level.name.capitalize()
        body["message"] = req.alert_message
    elif req.type is RequestType.ACKNOWLEDGE:
        body["originatingEventId"] = req.originating_event_id
        body["response"] = req.response
    elif req.type is RequestType.DEVICE_STATE_CHANGE:
        body["attribute"] = req.attribute
        body["type"] = req.state_type
        body["previousState"] = req.previous_state
        body["newState"] = req.new_state
    else:
        body.update(req.extras or {})
    return {"deviceToken": req.device_token, "type": req.type.value,
            "tenant": req.tenant, "request": body}


class BinaryEventDecoder:
    """Decode the compact flat binary format (the reference's
    sources/decoder/protobuf/ProtobufDeviceEventDecoder slot)."""

    # same format as encode_binary_request -> batchable via
    # engine.ingest_binary_batch (see JsonDeviceRequestDecoder.wire_tag)
    wire_tag = "binary"

    def decode(self, payload: bytes, metadata: dict[str, Any]) -> list[DecodedRequest]:
        try:
            ver, tid, tlen = struct.unpack_from("<BBH", payload, 0)
            if ver != _BIN_MAGIC_VERSION:
                raise EventDecodeException(f"unknown binary version {ver}")
            off = 4
            token = payload[off: off + tlen].decode()
            off += tlen
            (ts,) = struct.unpack_from("<q", payload, off)
            off += 8
            rtype = _BIN_TYPES.get(tid)
            if rtype is None:
                raise EventDecodeException(f"unknown binary type id {tid}")
            req = DecodedRequest(type=rtype, device_token=token,
                                 event_ts_ms=None if ts < 0 else ts,
                                 metadata=dict(metadata))
            if rtype is RequestType.DEVICE_MEASUREMENT:
                (n,) = struct.unpack_from("<H", payload, off)
                off += 2
                pairs = {}
                for _ in range(n):
                    (nlen,) = struct.unpack_from("<H", payload, off)
                    off += 2
                    name = payload[off: off + nlen].decode()
                    off += nlen
                    (val,) = struct.unpack_from("<d", payload, off)
                    off += 8
                    pairs[name] = val
                req.measurements = pairs
            elif rtype is RequestType.DEVICE_LOCATION:
                lat, lon, elev = struct.unpack_from("<ddd", payload, off)
                req.latitude = None if lat != lat else lat    # NaN = absent
                req.longitude = None if lon != lon else lon
                req.elevation = elev
            elif rtype is RequestType.DEVICE_ALERT:
                (tl,) = struct.unpack_from("<H", payload, off)
                off += 2
                req.alert_type = payload[off: off + tl].decode()
                off += tl
                (lvl,) = struct.unpack_from("<B", payload, off)
                off += 1
                req.alert_level = AlertLevel(lvl)
                (ml,) = struct.unpack_from("<H", payload, off)
                off += 2
                req.alert_message = payload[off: off + ml].decode() or None
            elif rtype is RequestType.REGISTER_DEVICE and off < len(payload):
                # body optional: header-only frames (older encoders) decode
                # with empty extras
                (n,) = struct.unpack_from("<H", payload, off)
                off += 2
                extras = {}
                for _ in range(n):
                    (kl,) = struct.unpack_from("<H", payload, off)
                    off += 2
                    key = payload[off: off + kl].decode()
                    off += kl
                    (vl,) = struct.unpack_from("<H", payload, off)
                    off += 2
                    extras[key] = payload[off: off + vl].decode()
                    off += vl
                req.extras = extras
            elif rtype is RequestType.ACKNOWLEDGE and off < len(payload):
                (ol,) = struct.unpack_from("<H", payload, off)
                off += 2
                req.originating_event_id = (
                    payload[off: off + ol].decode() or None)
                off += ol
                (rl,) = struct.unpack_from("<H", payload, off)
                off += 2
                req.response = payload[off: off + rl].decode() or None
            return [req]
        except (struct.error, UnicodeDecodeError, IndexError) as e:
            raise EventDecodeException(str(e)) from e


class ScriptedDecoder:
    """User-supplied decode function — the Python analog of the reference's
    Groovy ScriptedEventDecoder (sources/decoder/ScriptedEventDecoder.java:
    bindings for payload/metadata, returns request list)."""

    def __init__(self, fn: Callable[[bytes, dict], list[DecodedRequest]]):
        self.fn = fn

    def decode(self, payload: bytes, metadata: dict[str, Any]) -> list[DecodedRequest]:
        try:
            out = self.fn(payload, metadata)
        except Exception as e:  # user scripts fail -> decode failure DLQ
            raise EventDecodeException(f"scripted decoder error: {e}") from e
        if not isinstance(out, list):
            raise EventDecodeException("scripted decoder must return a list")
        return out


class CompositeDecoder:
    """Metadata-extractor + per-criteria delegation (reference:
    sources/decoder/composite/*): extract (device_type, payload') from the
    raw payload, then route to the decoder mapped for that device type."""

    def __init__(
        self,
        extractor: Callable[[bytes, dict], tuple[str, bytes]],
        choices: dict[str, EventDecoder],
        default: EventDecoder | None = None,
    ):
        self.extractor = extractor
        self.choices = choices
        self.default = default

    def decode(self, payload: bytes, metadata: dict[str, Any]) -> list[DecodedRequest]:
        try:
            key, inner = self.extractor(payload, metadata)
        except Exception as e:
            raise EventDecodeException(f"composite extractor error: {e}") from e
        decoder = self.choices.get(key, self.default)
        if decoder is None:
            raise EventDecodeException(f"no decoder mapped for {key!r}")
        return decoder.decode(inner, metadata)


class EchoStringDecoder:
    """Debug decoder: logs and drops (reference: debug/EchoStringDecoder)."""

    def decode(self, payload, metadata):
        logger.info("echo decoder: %r", payload)
        return []


class PayloadLoggerDecoder:
    """Debug wrapper: logs payload then delegates
    (reference: debug/PayloadLoggerEventDecoder)."""

    def __init__(self, delegate: EventDecoder):
        self.delegate = delegate

    def decode(self, payload, metadata):
        logger.info("payload (%d bytes): %r", len(payload), payload[:256])
        return self.delegate.decode(payload, metadata)
