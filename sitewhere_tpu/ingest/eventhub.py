"""Partitioned event hub: the Azure Event Hubs consumption model, in-process.

The reference consumes Azure Event Hubs through an ``EventProcessorHost``
(sources/azure/EventHubInboundEventReceiver.java): a named hub with fixed
partitions, a consumer group, one processor per owned partition receiving
*batches* (``onEvents``), offsets/sequence numbers per event, and periodic
checkpointing to a storage container every 5 events
(``checkpointBatchingCount % 5``, lines 77-92) so a restarted host resumes
from the last checkpoint. The Azure SDK and network egress don't exist in
this image, so the *consumption semantics* are implemented here natively:
``EventHub`` (partitioned log, partition-key hashing), ``CheckpointStore``
(per consumer-group/partition offsets, optionally file-backed),
``EventProcessorHost`` (partition ownership split across hosts of a group,
batch delivery, periodic checkpoint, resume), and the ingest receiver +
outbound connector built on them.

Legacy-compat receiver: delivery lands on the per-event
``InboundEventSource`` path. New high-rate device transports should use
the batched persistent-connection edge (``ingest/wire_edge.py``);
sources kept on this receiver inherit the manager's shared
``WireBatcher`` (batched arena submission) when their decoder declares
a ``wire_tag``.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import pathlib
import zlib
from collections import deque
from dataclasses import dataclass
from typing import Any, Awaitable, Callable

from sitewhere_tpu.ingest.sources import InboundEventReceiver

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class EventData:
    """One hub record (EventData analog: body + system properties)."""

    body: bytes
    offset: int
    sequence_number: int
    partition_id: int
    partition_key: str | None = None


class _Partition:
    """One retention-bounded partition log. ``base`` is the offset of the
    first retained event; offsets are absolute and survive trimming (Kafka/
    EventHub retention semantics)."""

    def __init__(self, retention: int):
        self.events: deque[EventData] = deque()
        self.base = 0
        self.retention = retention

    @property
    def end(self) -> int:
        return self.base + len(self.events)

    def append(self, ev: EventData) -> None:
        self.events.append(ev)
        while len(self.events) > self.retention:
            self.events.popleft()
            self.base += 1

    def read(self, from_offset: int, max_batch: int) -> list[EventData]:
        start = max(from_offset, self.base) - self.base
        return list(self.events)[start: start + max_batch]


class EventHub:
    """A named hub with a fixed number of retention-bounded partitions.

    Send with a partition key (stable hash, like the reference keying Kafka
    by device token) or round-robin without one. ``retention`` bounds each
    partition; readers behind the retention window age out to the oldest
    retained offset.
    """

    def __init__(self, name: str, partition_count: int = 4,
                 retention: int = 100_000):
        assert partition_count > 0
        self.name = name
        # log generation id: a checkpoint taken against a different (e.g.
        # pre-restart) hub instance must not be applied to this log
        self.epoch = os.urandom(8).hex()
        self.partitions: list[_Partition] = [
            _Partition(retention) for _ in range(partition_count)]
        self._rr = 0
        self._waiters: list[asyncio.Event] = []

    @property
    def partition_count(self) -> int:
        return len(self.partitions)

    def send(self, body: bytes, partition_key: str | None = None) -> EventData:
        if partition_key is not None:
            pid = zlib.crc32(partition_key.encode()) % self.partition_count
        else:
            pid = self._rr
            self._rr = (self._rr + 1) % self.partition_count
        part = self.partitions[pid]
        ev = EventData(body=body, offset=part.end,
                       sequence_number=part.end, partition_id=pid,
                       partition_key=partition_key)
        part.append(ev)
        for w in self._waiters:
            w.set()
        return ev

    def read(self, partition_id: int, from_offset: int,
             max_batch: int = 64) -> list[EventData]:
        return self.partitions[partition_id].read(from_offset, max_batch)

    def end_offset(self, partition_id: int) -> int:
        return self.partitions[partition_id].end

    def register_waiter(self, event: asyncio.Event) -> None:
        self._waiters.append(event)

    def unregister_waiter(self, event: asyncio.Event) -> None:
        if event in self._waiters:
            self._waiters.remove(event)


class CheckpointStore:
    """Per (consumer group, partition) offset checkpoints — the storage-
    container analog. Optionally file-backed so a new host resumes. Each
    checkpoint records the hub's log epoch; a checkpoint from a different
    log generation is ignored (resume from the log start, at-least-once)."""

    def __init__(self, path: str | pathlib.Path | None = None):
        self.path = pathlib.Path(path) if path is not None else None
        self._data: dict[str, dict] = {}
        if self.path is not None and self.path.exists():
            self._data = json.loads(self.path.read_text())

    @staticmethod
    def _key(group: str, partition_id: int) -> str:
        return f"{group}/{partition_id}"

    def get(self, group: str, partition_id: int, epoch: str) -> int:
        entry = self._data.get(self._key(group, partition_id))
        if entry is None or entry.get("epoch") != epoch:
            return 0
        return entry["offset"]

    def checkpoint(self, group: str, partition_id: int, next_offset: int,
                   epoch: str) -> None:
        self._data[self._key(group, partition_id)] = {
            "offset": next_offset, "epoch": epoch}
        if self.path is not None:
            self.path.write_text(json.dumps(self._data))


OnEvents = Callable[[int, list[EventData]], Awaitable[None] | None]


class EventProcessorHost:
    """Owns a subset of a hub's partitions for one consumer group and drives
    a processor callback with event batches, checkpointing every
    ``checkpoint_every`` events (reference default: 5)."""

    _groups: dict[tuple[int, str], list["EventProcessorHost"]] = {}

    def __init__(self, hub: EventHub, consumer_group: str,
                 store: CheckpointStore | None = None,
                 checkpoint_every: int = 5, max_batch: int = 64,
                 host_name: str = "host"):
        self.hub = hub
        self.consumer_group = consumer_group
        self.store = store or CheckpointStore()
        self.checkpoint_every = checkpoint_every
        self.max_batch = max_batch
        self.host_name = host_name
        self.on_events: OnEvents | None = None
        self._tasks: list[asyncio.Task] = []
        self._wake = asyncio.Event()
        self._since_checkpoint: dict[int, int] = {}
        self._next: dict[int, int] = {}

    def _group_key(self) -> tuple[int, str]:
        return (id(self.hub), self.consumer_group)

    def owned_partitions(self) -> list[int]:
        """Partitions leased to this host: the group's hosts split the
        partition space evenly (the EventProcessorHost lease analog)."""
        peers = self._groups.get(self._group_key(), [self])
        idx = peers.index(self)
        return [p for p in range(self.hub.partition_count)
                if p % len(peers) == idx]

    async def register(self) -> None:
        self._groups.setdefault(self._group_key(), []).append(self)
        self.hub.register_waiter(self._wake)
        self._tasks.append(asyncio.create_task(self._pump()))

    async def unregister(self) -> None:
        peers = self._groups.get(self._group_key(), [])
        if self in peers:
            peers.remove(self)
        self.hub.unregister_waiter(self._wake)
        for t in self._tasks:
            t.cancel()
        self._tasks.clear()

    async def _pump(self) -> None:
        try:
            while True:
                drained = await self._drain_once()
                if not drained:
                    self._wake.clear()
                    try:
                        await asyncio.wait_for(self._wake.wait(), 0.5)
                    except asyncio.TimeoutError:
                        pass
        except asyncio.CancelledError:
            pass

    async def _drain_once(self) -> bool:
        any_events = False
        for pid in self.owned_partitions():
            if pid not in self._next:
                self._next[pid] = self.store.get(self.consumer_group, pid,
                                                 self.hub.epoch)
                self._since_checkpoint[pid] = 0
            batch = self.hub.read(pid, self._next[pid], self.max_batch)
            if not batch:
                continue
            any_events = True
            if self.on_events is not None:
                res = self.on_events(pid, batch)
                if asyncio.iscoroutine(res):
                    await res
            # offsets are absolute; a reader behind the retention window
            # ages out to wherever the log actually resumed
            self._next[pid] = batch[-1].offset + 1
            self._since_checkpoint[pid] += len(batch)
            if self._since_checkpoint[pid] >= self.checkpoint_every:
                self.store.checkpoint(self.consumer_group, pid,
                                      self._next[pid], self.hub.epoch)
                self._since_checkpoint[pid] = 0
        return any_events


class EventHubEventReceiver(InboundEventReceiver):
    """Consume a hub through a processor host and submit payloads to the
    event source (reference: sources/azure/EventHubInboundEventReceiver)."""

    def __init__(self, hub: EventHub, consumer_group: str = "$Default",
                 store: CheckpointStore | None = None,
                 checkpoint_every: int = 5):
        super().__init__(f"eventhub:{hub.name}")
        self.host = EventProcessorHost(hub, consumer_group, store,
                                       checkpoint_every)

    async def on_start(self) -> None:
        async def on_events(pid: int, batch: list[EventData]) -> None:
            for ev in batch:
                self.submit(ev.body, {"partition": pid, "offset": ev.offset})

        self.host.on_events = on_events
        await self.host.register()

    async def on_stop(self) -> None:
        await self.host.unregister()
