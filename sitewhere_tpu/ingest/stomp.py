"""Native STOMP 1.2: frame codec, asyncio client, embedded broker, and the
ActiveMQ-equivalent receivers.

The reference has two ActiveMQ ingestion modes: an *embedded broker* started
inside the receiver with a transport connector and a consumer pool on a named
queue (sources/activemq/ActiveMqBrokerEventReceiver.java:67-95 — broker name
and queue name are required config, JMX/shutdown hooks disabled), and a
*client* that attaches to a remote broker and runs N competing consumers on
a queue (sources/activemq/ActiveMqClientEventReceiver.java:64-155). ActiveMQ
speaks OpenWire/JMS; the open text protocol it also ships is STOMP, so the
TPU build implements STOMP 1.2 here — queue destinations get point-to-point
round-robin delivery (JMS queue semantics, competing consumers), topic
destinations get fan-out (JMS topic semantics).

Legacy-compat receiver: frames submit one payload at a time through
``InboundEventSource``. New high-rate device transports should front
the batched persistent-connection edge (``ingest/wire_edge.py``);
sources kept on this receiver inherit the manager's shared
``WireBatcher`` (batched arena submission) when their decoder declares
a ``wire_tag``.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from collections import deque
from typing import Any, Callable

from sitewhere_tpu.ingest.sources import InboundEventReceiver

logger = logging.getLogger(__name__)

_ESCAPES = {"\\": "\\\\", "\r": "\\r", "\n": "\\n", ":": "\\c"}
_UNESCAPES = {"\\\\": "\\", "\\r": "\r", "\\n": "\n", "\\c": ":"}


def _escape(s: str) -> str:
    return "".join(_ESCAPES.get(c, c) for c in s)


def _unescape(s: str) -> str:
    out, i = [], 0
    while i < len(s):
        if s[i] == "\\" and i + 1 < len(s):
            out.append(_UNESCAPES.get(s[i: i + 2], s[i + 1]))
            i += 2
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


def encode_frame(command: str, headers: dict[str, str], body: bytes = b"") -> bytes:
    lines = [command]
    hdrs = dict(headers)
    if body:
        hdrs.setdefault("content-length", str(len(body)))
    for k, v in hdrs.items():
        lines.append(f"{_escape(k)}:{_escape(v)}")
    return ("\n".join(lines) + "\n\n").encode() + body + b"\x00"


async def read_frame(reader: asyncio.StreamReader) -> tuple[str, dict[str, str], bytes]:
    # skip heart-beat newlines between frames
    while True:
        first = await reader.readexactly(1)
        if first not in (b"\n", b"\r"):
            break
    line = first + (await reader.readuntil(b"\n"))
    command = line.decode().strip()
    headers: dict[str, str] = {}
    while True:
        raw = (await reader.readuntil(b"\n")).decode().rstrip("\r\n")
        if not raw:
            break
        key, _, val = raw.partition(":")
        headers.setdefault(_unescape(key), _unescape(val))
    if "content-length" in headers:
        n = int(headers["content-length"])
        body = await reader.readexactly(n)
        await reader.readexactly(1)  # trailing NUL
    else:
        body = (await reader.readuntil(b"\x00"))[:-1]
    return command, headers, body


class _Dest:
    def __init__(self, name: str):
        self.name = name
        self.queue = name.startswith("/queue/")
        # (body, passthrough headers) buffered while no subscriber (queues)
        self.pending: deque[tuple[bytes, dict[str, str]]] = deque()
        # (writer, subscription id) in subscribe order
        self.subs: deque[tuple[asyncio.StreamWriter, str]] = deque()


class StompBroker:
    """Embedded STOMP broker: /queue/* point-to-point round-robin with
    buffering, /topic/* fan-out (the BrokerService analog of
    ActiveMqBrokerEventReceiver.java:76-95)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 broker_name: str = "sitewhere"):
        self.host, self.port = host, port
        self.broker_name = broker_name
        self._server: asyncio.AbstractServer | None = None
        self.dests: dict[str, _Dest] = {}
        self._writers: set[asyncio.StreamWriter] = set()

    @property
    def bound_port(self) -> int:
        assert self._server is not None and self._server.sockets
        return self._server.sockets[0].getsockname()[1]

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)

    async def stop(self) -> None:
        for w in list(self._writers):
            w.close()
        self._writers.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _send_to(self, dest: _Dest, body: bytes,
                       headers: dict[str, str]) -> None:
        msg_headers = {"destination": dest.name,
                       "message-id": headers.get("message-id", "m-0"),
                       "subscription": ""}
        passthrough = {k: v for k, v in headers.items()
                       if k not in ("destination", "content-length", "receipt")}
        if dest.queue:
            while dest.subs:
                writer, sub_id = dest.subs[0]
                if writer.is_closing():
                    dest.subs.popleft()
                    continue
                dest.subs.rotate(-1)
                try:
                    writer.write(encode_frame(
                        "MESSAGE", {**msg_headers, **passthrough,
                                    "subscription": sub_id}, body))
                    await writer.drain()
                    return
                except ConnectionError:
                    # the failing writer was rotated to the back; remove it
                    # specifically, not whoever is now at the front
                    dest.subs = deque(
                        (w, s) for w, s in dest.subs if w is not writer)
            dest.pending.append((body, passthrough))
        else:
            for writer, sub_id in list(dest.subs):
                try:
                    writer.write(encode_frame(
                        "MESSAGE", {**msg_headers, **passthrough,
                                    "subscription": sub_id}, body))
                    await writer.drain()
                except ConnectionError:
                    pass

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        self._writers.add(writer)
        msg_ids = itertools.count(1)
        try:
            while True:
                command, headers, body = await read_frame(reader)
                if command in ("CONNECT", "STOMP"):
                    writer.write(encode_frame("CONNECTED", {
                        "version": "1.2", "server": self.broker_name}))
                elif command == "SUBSCRIBE":
                    name = headers["destination"]
                    dest = self.dests.setdefault(name, _Dest(name))
                    dest.subs.append((writer, headers.get("id", "0")))
                    while dest.queue and dest.pending and dest.subs:
                        p_body, p_headers = dest.pending.popleft()
                        await self._send_to(
                            dest, p_body,
                            {**p_headers, "message-id": f"m-{next(msg_ids)}"})
                elif command == "UNSUBSCRIBE":
                    sub_id = headers.get("id", "0")
                    for dest in self.dests.values():
                        dest.subs = deque(
                            (w, s) for w, s in dest.subs
                            if not (w is writer and s == sub_id))
                elif command == "SEND":
                    name = headers["destination"]
                    dest = self.dests.setdefault(name, _Dest(name))
                    await self._send_to(
                        dest, body,
                        {**headers, "message-id": f"m-{next(msg_ids)}"})
                elif command == "DISCONNECT":
                    if "receipt" in headers:
                        writer.write(encode_frame(
                            "RECEIPT", {"receipt-id": headers["receipt"]}))
                        await writer.drain()
                    break
                if command != "DISCONNECT":
                    await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError, ValueError):
            pass
        finally:
            self._writers.discard(writer)
            for dest in self.dests.values():
                dest.subs = deque((w, s) for w, s in dest.subs if w is not writer)
            writer.close()


class StompClient:
    """Minimal asyncio STOMP 1.2 client."""

    def __init__(self, host: str, port: int):
        self.host, self.port = host, port
        self.on_message: Callable[[str, dict[str, str], bytes], Any] | None = None
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._task: asyncio.Task | None = None
        self._sub_ids = itertools.count(1)

    async def connect(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(self.host, self.port)
        self._writer.write(encode_frame("CONNECT", {
            "accept-version": "1.2", "host": self.host}))
        await self._writer.drain()
        command, headers, _ = await read_frame(self._reader)
        if command != "CONNECTED":
            raise ConnectionError(f"STOMP connect refused: {command} {headers}")
        self._task = asyncio.create_task(self._read_loop())

    async def _read_loop(self) -> None:
        try:
            while True:
                command, headers, body = await read_frame(self._reader)
                if command == "MESSAGE" and self.on_message is not None:
                    res = self.on_message(headers.get("destination", ""),
                                          headers, body)
                    if asyncio.iscoroutine(res):
                        await res
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.CancelledError):
            pass

    async def subscribe(self, destination: str) -> str:
        sub_id = f"sub-{next(self._sub_ids)}"
        self._writer.write(encode_frame("SUBSCRIBE", {
            "id": sub_id, "destination": destination, "ack": "auto"}))
        await self._writer.drain()
        return sub_id

    async def send(self, destination: str, body: bytes,
                   headers: dict[str, str] | None = None) -> None:
        self._writer.write(encode_frame(
            "SEND", {"destination": destination, **(headers or {})}, body))
        await self._writer.drain()

    async def disconnect(self) -> None:
        if self._task is not None:
            self._task.cancel()
        if self._writer is not None:
            try:
                self._writer.write(encode_frame("DISCONNECT", {}))
                await self._writer.drain()
            except ConnectionError:
                pass
            self._writer.close()
            self._writer = None


class ActiveMqBrokerEventReceiver(InboundEventReceiver):
    """Embedded-broker receiver: starts a broker and consumes a queue on it
    (reference: sources/activemq/ActiveMqBrokerEventReceiver.java:67-95 —
    broker name and queue name are required)."""

    def __init__(self, broker_name: str, queue_name: str,
                 host: str = "127.0.0.1", port: int = 0,
                 num_consumers: int = 3):
        if not broker_name:
            raise ValueError("Broker name must be configured.")
        if not queue_name:
            raise ValueError("Queue name must be configured.")
        super().__init__(f"activemq-broker:{queue_name}")
        self.broker = StompBroker(host, port, broker_name)
        self.queue_name = queue_name
        self.num_consumers = num_consumers
        self._clients: list[StompClient] = []

    @property
    def bound_port(self) -> int:
        return self.broker.bound_port

    async def on_start(self) -> None:
        await self.broker.start()
        for _ in range(self.num_consumers):
            client = StompClient("127.0.0.1", self.broker.bound_port)
            client.on_message = lambda dest, headers, body: self.submit(
                body, {"destination": dest})
            await client.connect()
            await client.subscribe(f"/queue/{self.queue_name}")
            self._clients.append(client)

    async def on_stop(self) -> None:
        for client in self._clients:
            await client.disconnect()
        self._clients.clear()
        await self.broker.stop()


class ActiveMqClientEventReceiver(InboundEventReceiver):
    """Remote-broker receiver: N competing consumers on a queue (reference:
    sources/activemq/ActiveMqClientEventReceiver.java:64-155)."""

    def __init__(self, host: str, port: int, queue_name: str,
                 num_consumers: int = 3):
        if not queue_name:
            raise ValueError("Queue name must be configured.")
        super().__init__(f"activemq-client:{queue_name}")
        self.host, self.port = host, port
        self.queue_name = queue_name
        self.num_consumers = num_consumers
        self._clients: list[StompClient] = []

    async def on_start(self) -> None:
        for _ in range(self.num_consumers):
            client = StompClient(self.host, self.port)
            client.on_message = lambda dest, headers, body: self.submit(
                body, {"destination": dest})
            await client.connect()
            await client.subscribe(f"/queue/{self.queue_name}")
            self._clients.append(client)

    async def on_stop(self) -> None:
        for client in self._clients:
            await client.disconnect()
        self._clients.clear()
