"""Native CoAP (RFC 7252) subset: message codec + UDP server receiver +
client for command delivery.

The reference runs an Eclipse Californium CoAP server for ingest
(sources/coap/CoapServerEventReceiver.java:23-62 + CoapMessageDeliverer) and
a Californium client for command destinations (commands destination/coap/*).
No CoAP library ships here, so the needed subset is implemented directly:
confirmable/non-confirmable POST/PUT with ACK piggyback responses, token +
option parsing (Uri-Path), and a matching client.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable

from sitewhere_tpu.ingest.sources import InboundEventReceiver

logger = logging.getLogger(__name__)

# message types
CON, NON, ACK, RST = 0, 1, 2, 3
# method / response codes
GET, POST, PUT, DELETE = 1, 2, 3, 4
CREATED, CHANGED, CONTENT = 0x41, 0x44, 0x45
BAD_REQUEST, NOT_FOUND = 0x80, 0x84
OPT_URI_PATH = 11
PAYLOAD_MARKER = 0xFF


def encode_message(mtype: int, code: int, message_id: int, token: bytes = b"",
                   uri_path: list[str] | None = None, payload: bytes = b"") -> bytes:
    out = bytearray()
    out.append(0x40 | (mtype << 4) | len(token))  # version 1
    out.append(code)
    out += message_id.to_bytes(2, "big")
    out += token
    prev = 0
    for seg in uri_path or []:
        delta = OPT_URI_PATH - prev
        seg_b = seg.encode()
        if delta > 12 or len(seg_b) > 12:
            # extended option encoding (delta/length 13..268)
            d = min(delta, 13) if delta > 12 else delta
            ln = 13 if len(seg_b) > 12 else len(seg_b)
            out.append((d << 4) | ln)
            if d == 13:
                out.append(delta - 13)
            if ln == 13:
                out.append(len(seg_b) - 13)
        else:
            out.append((delta << 4) | len(seg_b))
        out += seg_b
        prev = OPT_URI_PATH
    if payload:
        out.append(PAYLOAD_MARKER)
        out += payload
    return bytes(out)


def decode_message(data: bytes) -> dict:
    if len(data) < 4 or (data[0] >> 6) != 1:
        raise ValueError("not a CoAP v1 message")
    tkl = data[0] & 0x0F
    msg = {
        "type": (data[0] >> 4) & 0x03,
        "code": data[1],
        "message_id": int.from_bytes(data[2:4], "big"),
        "token": data[4: 4 + tkl],
        "uri_path": [],
        "payload": b"",
    }
    off = 4 + tkl
    opt = 0
    while off < len(data):
        if data[off] == PAYLOAD_MARKER:
            msg["payload"] = data[off + 1:]
            break
        delta, ln = data[off] >> 4, data[off] & 0x0F
        off += 1
        if delta == 13:
            delta = 13 + data[off]
            off += 1
        if ln == 13:
            ln = 13 + data[off]
            off += 1
        opt += delta
        val = data[off: off + ln]
        off += ln
        if opt == OPT_URI_PATH:
            msg["uri_path"].append(val.decode())
    return msg


class _ServerProtocol(asyncio.DatagramProtocol):
    def __init__(self, handler: Callable[[dict, tuple], bytes | None]):
        self.handler = handler
        self.transport: asyncio.DatagramTransport | None = None

    def connection_made(self, transport):
        self.transport = transport

    def datagram_received(self, data, addr):
        try:
            msg = decode_message(data)
        except ValueError:
            return
        reply = self.handler(msg, addr)
        if reply is not None:
            self.transport.sendto(reply, addr)


class CoapServerEventReceiver(InboundEventReceiver):
    """CoAP ingest endpoint: POST/PUT to any path submits the payload
    (reference: CoapServerEventReceiver + CoapMessageDeliverer routing)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        super().__init__(f"coap:{port}")
        self.host, self.port = host, port
        self._transport: asyncio.DatagramTransport | None = None
        self._loop: asyncio.AbstractEventLoop | None = None

    @property
    def bound_port(self) -> int:
        assert self._transport is not None
        return self._transport.get_extra_info("sockname")[1]

    def _handle(self, msg: dict, addr: tuple) -> bytes | None:
        if msg["code"] in (POST, PUT):
            code = CREATED if msg["code"] == POST else CHANGED
            meta = {"uri_path": "/".join(msg["uri_path"]), "remote": str(addr)}
            batched = (self.source is not None
                       and self.source.batcher is not None
                       and self.source._wire_tag is not None)
            if batched and msg["type"] == CON:
                # WAL-before-ack: on the batched path the piggyback ACK
                # would outrun durability, so withhold it and send a
                # detached ACK once the batch clears the durability gate
                # (on_durable fires on the flusher thread — marshal the
                # sendto back onto the receiver's loop).
                ack = encode_message(ACK, code, msg["message_id"], msg["token"])

                def _send_ack() -> None:
                    if self._transport is not None:
                        self._transport.sendto(ack, addr)

                def _on_durable() -> None:
                    if self._loop is not None and not self._loop.is_closed():
                        self._loop.call_soon_threadsafe(_send_ack)

                self.submit(msg["payload"], meta, on_durable=_on_durable)
                return None
            self.submit(msg["payload"], meta)
        elif msg["code"] == 0:  # empty/ping
            return encode_message(RST, 0, msg["message_id"])
        else:
            code = BAD_REQUEST
        if msg["type"] == CON:
            return encode_message(ACK, code, msg["message_id"], msg["token"])
        return None

    async def on_start(self) -> None:
        loop = asyncio.get_running_loop()
        self._loop = loop
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: _ServerProtocol(self._handle), local_addr=(self.host, self.port)
        )

    async def on_stop(self) -> None:
        if self._transport is not None:
            self._transport.close()
            self._transport = None


class CoapClient:
    """Fire a confirmable request and await the ACK (command delivery)."""

    def __init__(self, host: str, port: int, timeout: float = 5.0):
        self.host, self.port, self.timeout = host, port, timeout
        self._mid = 0

    async def request(self, code: int, uri_path: list[str], payload: bytes = b"") -> dict:
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._mid = (self._mid + 1) % 0xFFFF

        class _P(asyncio.DatagramProtocol):
            def connection_made(self, transport):
                self.transport = transport

            def datagram_received(self, data, addr):
                if not fut.done():
                    try:
                        fut.set_result(decode_message(data))
                    except ValueError:
                        pass

        transport, _ = await loop.create_datagram_endpoint(
            _P, remote_addr=(self.host, self.port)
        )
        try:
            transport.sendto(
                encode_message(CON, code, self._mid, b"\x01", uri_path, payload)
            )
            return await asyncio.wait_for(fut, self.timeout)
        finally:
            transport.close()
