"""Multi-worker host ingest: N decode/staging processes feed one engine.

SURVEY.md §2.9 maps the reference's replica parallelism (each microservice
scales horizontally behind partitioned Kafka consumer groups) to "multiple
host ingest workers feeding a fixed chip mesh". The single-process ingest
path tops out at one core's JSON-scan rate; this pool runs the C++ scanner
(native/src/swtpu.cpp) in ``n_workers`` separate processes, each decoding
wire batches into SHARED-MEMORY SoA staging, with the engine process only
translating dictionary ids and dispatching device programs.

Dictionary federation (the crux): each worker owns LOCAL interners for
device tokens / measurement names / alert types (interner state cannot be
shared across processes). Workers report newly-interned strings once, the
engine maintains per-worker translation tables, and steady-state batches
translate with pure numpy gathers — no per-event Python, no string traffic.
Measurement names additionally need a LANE permutation (a name's value
lands in lane ``name_id % channels``, and worker name ids diverge from the
engine's); if a worker's lane mapping ever becomes ambiguous (same worker
lane claimed by names that map to different engine lanes — requires an
in-worker lane collision, which the single-path decoder also mishandles
only by aliasing) the pool falls back to engine-side decode for that
worker's batches, trading speed for exactness.

Workers never import jax; the engine process keeps sole ownership of the
device. On a 1-core host the pool degrades to a single worker and roughly
matches the in-process path; with spare cores the scan work scales out.
"""

from __future__ import annotations

import logging
import multiprocessing as mp
import os
from multiprocessing import shared_memory

import numpy as np

logger = logging.getLogger(__name__)

_HDR = 8  # int64 header slots in shm_in: [n_msgs, buf_len, ...reserved]


def _shm_arrays(buf, max_msgs: int, channels: int):
    """Carve the output SoA views out of one shared-memory block."""
    b, c = max_msgs, channels
    off = 0

    def take(dtype, shape):
        nonlocal off
        n = int(np.prod(shape)) * np.dtype(dtype).itemsize
        a = np.ndarray(shape, dtype, buffer=buf, offset=off)
        off += n
        return a

    return {
        "rtype": take(np.int32, (b,)),
        "token": take(np.int32, (b,)),
        "ts": take(np.int64, (b,)),
        "values": take(np.float32, (b, c)),
        "chmask": take(np.uint8, (b, c)),
        "aux0": take(np.int32, (b,)),
        "level": take(np.int32, (b,)),
    }


def _out_bytes(max_msgs: int, channels: int) -> int:
    return max_msgs * (4 + 4 + 8 + 4 * channels + channels + 4 + 4)


def _worker_main(conn, in_name: str, out_name: str, max_msgs: int,
                 max_bytes: int, channels: int, token_capacity: int) -> None:
    """One decode worker: wire batch in shm_in -> SoA in shm_out.
    Replies ("done", n_ok, collisions, new_tokens, new_names, new_alerts)
    where the new_* lists carry strings interned FOR THE FIRST TIME by this
    batch, in local-id order (the engine extends its translation tables
    from exactly these)."""
    from sitewhere_tpu.ingest.fast_decode import NativeBatchDecoder
    from sitewhere_tpu.native.binding import NativeInterner

    shm_in = shared_memory.SharedMemory(name=in_name)
    shm_out = shared_memory.SharedMemory(name=out_name)
    try:
        hdr = np.ndarray((_HDR,), np.int64, buffer=shm_in.buf)
        offsets = np.ndarray((max_msgs + 1,), np.int64, buffer=shm_in.buf,
                             offset=_HDR * 8)
        data_off = _HDR * 8 + (max_msgs + 1) * 8
        out = _shm_arrays(shm_out.buf, max_msgs, channels)

        tokens = NativeInterner(token_capacity)
        dec = NativeBatchDecoder(tokens, channels)
        n_tok = n_name = n_alert = 0

        def tail(interner, since: int) -> list[str]:
            return [interner.token(i) for i in range(since, len(interner))]

        while True:
            msg = conn.recv()
            if msg is None:
                break
            n = int(hdr[0])
            payloads_buf = bytes(shm_in.buf[data_off:data_off + int(hdr[1])])
            # one scanner call over the whole batch, straight into shm
            n_ok, collisions = dec.decode_packed(
                payloads_buf, offsets, n, out["rtype"], out["token"],
                out["ts"], out["values"], out["chmask"], out["aux0"],
                out["level"])
            new_tokens = tail(tokens, n_tok)
            new_names = tail(dec.names, n_name)
            new_alerts = tail(dec.alert_types, n_alert)
            n_tok += len(new_tokens)
            n_name += len(new_names)
            n_alert += len(new_alerts)
            conn.send(("done", n_ok, collisions,
                       new_tokens, new_names, new_alerts))
    finally:
        shm_in.close()
        shm_out.close()
        conn.close()


class _Worker:
    def __init__(self, idx: int, max_msgs: int, max_bytes: int,
                 channels: int, token_capacity: int, ctx):
        in_bytes = _HDR * 8 + (max_msgs + 1) * 8 + max_bytes
        self.shm_in = shared_memory.SharedMemory(
            create=True, size=in_bytes)
        self.shm_out = shared_memory.SharedMemory(
            create=True, size=_out_bytes(max_msgs, channels))
        self.hdr = np.ndarray((_HDR,), np.int64, buffer=self.shm_in.buf)
        self.offsets = np.ndarray((max_msgs + 1,), np.int64,
                                  buffer=self.shm_in.buf, offset=_HDR * 8)
        self.data_off = _HDR * 8 + (max_msgs + 1) * 8
        self.out = _shm_arrays(self.shm_out.buf, max_msgs, channels)
        self.conn, child = ctx.Pipe()
        self.proc = ctx.Process(
            target=_worker_main,
            args=(child, self.shm_in.name, self.shm_out.name, max_msgs,
                  max_bytes, channels, token_capacity),
            daemon=True)
        self.proc.start()
        child.close()
        # engine-side translation state
        self.tok_map = np.empty(0, np.int32)
        self.alert_map = np.empty(0, np.int32)
        self.lane_owner: dict[int, int] = {}   # worker lane -> engine lane
        self.elane_owner: dict[int, int] = {}  # engine lane -> worker lane
        self.n_names_seen = 0   # dense worker-local name ids handed out
        self.lane_conflict = False
        self.pending: tuple[list[bytes], str] | None = None

    def close(self) -> None:
        try:
            self.conn.send(None)
        except (BrokenPipeError, OSError):
            pass
        self.proc.join(timeout=5)
        if self.proc.is_alive():
            self.proc.kill()
            self.proc.join(timeout=5)
        self.conn.close()
        for shm in (self.shm_in, self.shm_out):
            shm.close()
            try:
                shm.unlink()
            except FileNotFoundError:
                pass


class DecodeWorkerPool:
    """Round-robin pool of decode workers in front of one engine.

    ``submit()`` hands a wire batch to the next worker and returns
    immediately (absorbing that worker's previous batch first if still
    outstanding); ``flush()`` absorbs everything. Ingest summaries come
    back from the absorb step with the same shape as
    ``engine.ingest_json_batch``."""

    def __init__(self, engine, n_workers: int | None = None,
                 max_msgs: int | None = None, max_bytes: int = 1 << 24):
        from sitewhere_tpu.ingest.fast_decode import native_available

        if not native_available():
            raise RuntimeError("native library unavailable")
        if engine.config.strict_channels:
            # the strict contract (reject + roll back a batch that would
            # exceed channel capacity, engine._check_strict_native) cannot
            # be enforced from worker-local interners — a colliding batch
            # would be WAL-logged and staged before the engine could see
            # the collision. Refuse loudly instead of silently degrading.
            raise ValueError(
                "DecodeWorkerPool does not support strict_channels engines;"
                " use the in-process ingest path")
        self.engine = engine
        self.channels = engine.config.channels
        self.n_workers = n_workers or max(1, (os.cpu_count() or 1) - 1)
        self.max_msgs = max_msgs or max(16384, engine.config.batch_capacity)
        self.max_bytes = max_bytes
        ctx = mp.get_context("spawn")   # workers must not inherit jax state
        self.workers = [
            _Worker(i, self.max_msgs, max_bytes, self.channels,
                    engine.config.token_capacity, ctx)
            for i in range(self.n_workers)
        ]
        self._next = 0
        self.summaries: list[dict] = []
        self.fallback_batches = 0

    # ------------------------------------------------------------ engine side
    def _absorb(self, w: _Worker) -> dict | None:
        if w.pending is None:
            return None
        payloads, tenant = w.pending
        w.pending = None
        kind, n_ok, collisions, new_tokens, new_names, new_alerts = \
            w.conn.recv()
        assert kind == "done"
        eng = self.engine
        # ---- extend translation tables from first-seen strings ----------
        # Under eng.lock: these interners are shared with REST registration
        # and in-process ingest, which all intern under the same lock.
        with eng.lock:
            if new_tokens:
                w.tok_map = np.concatenate([
                    w.tok_map,
                    np.fromiter((eng.tokens.intern(t) for t in new_tokens),
                                np.int32, len(new_tokens))])
            if new_alerts:
                w.alert_map = np.concatenate([
                    w.alert_map,
                    np.fromiter(
                        (eng.alert_types.intern(t) for t in new_alerts),
                        np.int32, len(new_alerts))])
            if new_names:
                names_interner = (eng._native_decoder.names
                                  if eng._native_decoder else None)
                for name in new_names:
                    wid = w.n_names_seen   # dense worker-local name id order
                    w.n_names_seen += 1
                    eid = (names_interner.intern(name) if names_interner
                           else eng.channel_map.names.intern(name))
                    wlane, elane = wid % self.channels, eid % self.channels
                    prev = w.lane_owner.get(wlane)
                    if prev is None:
                        # the engine lane must not already belong to a
                        # DIFFERENT worker lane — a non-injective map would
                        # let one lane's scatter clobber the other's
                        # (silent data loss)
                        if w.elane_owner.get(elane, wlane) != wlane:
                            w.lane_conflict = True
                        w.lane_owner[wlane] = elane
                        w.elane_owner[elane] = wlane
                    elif prev != elane:
                        w.lane_conflict = True
        n = len(payloads)
        if w.lane_conflict:
            # ambiguous lane permutation: exactness over speed — decode
            # this worker's batches in-engine from the raw payloads.
            # Surfaced as an engine metric so operators see the pool
            # degrading, not just a log line (VERDICT r3 weak #1)
            self.fallback_batches += 1
            with eng.lock:
                eng.host_counters["worker_fallback_batches"] = \
                    eng.host_counters.get("worker_fallback_batches", 0) + 1
            return eng.ingest_json_batch(payloads, tenant=tenant)
        # ---- translate + stage (numpy gathers only) ---------------------
        from sitewhere_tpu.engine import WAL_JSON
        from sitewhere_tpu.ingest.decoders import JsonDeviceRequestDecoder
        from sitewhere_tpu.ingest.fast_decode import (RT_ALERT,
                                                      RT_MEASUREMENT,
                                                      DecodedArrays)

        # shm views, NOT copies: the engine's staging (arena copy or
        # legacy buffer slices) completes synchronously inside
        # _ingest_decoded below, before this worker can be handed its
        # next batch — so the worker never overwrites a view in use.
        o = w.out
        rtype = o["rtype"][:n]
        token = o["token"][:n]
        gtok = (w.tok_map[np.clip(token, 0, max(0, len(w.tok_map) - 1))]
                if len(w.tok_map) else np.full(n, -1, np.int32))
        gtok = np.where(rtype >= 0, gtok, -1).astype(np.int32)
        # scatter ONLY lanes that carry data (every data-carrying worker
        # lane has a name behind it, hence an entry in lane_owner);
        # unmapped lanes must never overwrite a mapped engine lane
        if all(wl == el for wl, el in w.lane_owner.items()):
            values = o["values"][:n]
            chmask = o["chmask"][:n].astype(bool)
        else:
            wl = np.fromiter(w.lane_owner.keys(), np.int64,
                             len(w.lane_owner))
            el = np.fromiter(w.lane_owner.values(), np.int64,
                             len(w.lane_owner))
            raw_v = o["values"][:n]
            raw_m = o["chmask"][:n].astype(bool)
            values = np.zeros((n, self.channels), np.float32)
            chmask = np.zeros((n, self.channels), bool)
            values[:, el] = raw_v[:, wl]
            chmask[:, el] = raw_m[:, wl]
            # the lane permutation is derived from measurement names only;
            # LOCATION rows carry lat/lon/elev in FIXED lanes 0-2 (see
            # swtpu.cpp scan_location) and other non-measurement rows use
            # raw lanes — keep their lanes untouched
            nonmeas = rtype != RT_MEASUREMENT
            if np.any(nonmeas):
                values[nonmeas] = raw_v[nonmeas]
                chmask[nonmeas] = raw_m[nonmeas]
        aux0 = o["aux0"][:n]
        alert_rows = rtype == RT_ALERT
        if np.any(alert_rows) and len(w.alert_map):
            # in-place alert-type translation on the shm view is safe:
            # this slot is dead until the worker's next batch overwrites it
            aux0[alert_rows] = w.alert_map[
                np.clip(aux0[alert_rows], 0, len(w.alert_map) - 1)]
        res = DecodedArrays(
            n_ok=int(np.sum(rtype >= 0)), rtype=rtype, token_id=gtok,
            ts_ms64=o["ts"][:n], values=values, chmask=chmask,
            aux0=aux0, level=o["level"][:n], collisions=collisions)
        with eng.lock:
            eng._wal_append(WAL_JSON, payloads, tenant)
            # _ingest_decoded routes through the engine's staging arenas
            # when they exist: ONE vectorized shm->arena copy replaces
            # the DecodedArrays copies + HostEventBuffer staging pass
            return eng._ingest_decoded(res, payloads, tenant,
                                       JsonDeviceRequestDecoder())

    def submit(self, payloads: list[bytes], tenant: str = "default") -> None:
        """Queue one wire batch on the next worker (absorbs that worker's
        outstanding batch first, so at most one batch is in flight per
        worker)."""
        w = self.workers[self._next]
        self._next = (self._next + 1) % self.n_workers
        s = self._absorb(w)
        if s is not None:
            self.summaries.append(s)
        n = len(payloads)
        if n > self.max_msgs:
            raise ValueError(f"batch of {n} exceeds max_msgs {self.max_msgs}")
        lens = np.fromiter((len(p) for p in payloads), np.int64, n)
        total = int(lens.sum())
        if total > self.max_bytes:
            raise ValueError(
                f"batch of {total} payload bytes exceeds the pool's "
                f"max_bytes {self.max_bytes}; raise max_bytes or split "
                "the batch")
        self.offsets_fill(w, lens)
        buf = b"".join(payloads)
        w.shm_in.buf[w.data_off:w.data_off + len(buf)] = buf
        w.hdr[0], w.hdr[1] = n, len(buf)
        w.pending = (payloads, tenant)
        w.conn.send(("decode",))

    @staticmethod
    def offsets_fill(w: _Worker, lens: np.ndarray) -> None:
        w.offsets[0] = 0
        np.cumsum(lens, out=w.offsets[1:1 + len(lens)])

    def flush(self) -> list[dict]:
        """Absorb every outstanding batch; returns their summaries."""
        out, self.summaries = self.summaries, []
        for w in self.workers:
            s = self._absorb(w)
            if s is not None:
                out.append(s)
        return out

    def stats(self) -> dict:
        return {
            "n_workers": self.n_workers,
            "fallback_batches": self.fallback_batches,
            "lane_conflicts": sum(1 for w in self.workers if w.lane_conflict),
        }

    def close(self) -> None:
        self.flush()
        for w in self.workers:
            w.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
