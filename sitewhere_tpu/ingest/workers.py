"""Multi-worker host ingest: N decode/staging processes feed one engine.

SURVEY.md §2.9 maps the reference's replica parallelism (each microservice
scales horizontally behind partitioned Kafka consumer groups) to "multiple
host ingest workers feeding a fixed chip mesh". The single-process ingest
path tops out at one core's JSON-scan rate; this pool runs the C++ scanner
(native/src/swtpu.cpp) in ``n_workers`` separate processes, each decoding
wire batches into SHARED-MEMORY SoA staging, with the engine process only
translating dictionary ids and dispatching device programs.

Dictionary federation (the crux): each worker owns LOCAL interners for
device tokens / measurement names / alert types (interner state cannot be
shared across processes). Workers report newly-interned strings once, the
engine maintains per-worker translation tables, and steady-state batches
translate with pure numpy gathers — no per-event Python, no string traffic.
Measurement names additionally need a LANE permutation (a name's value
lands in lane ``name_id % channels``, and worker name ids diverge from the
engine's); if a worker's lane mapping ever becomes ambiguous (same worker
lane claimed by names that map to different engine lanes — requires an
in-worker lane collision, which the single-path decoder also mishandles
only by aliasing) the pool falls back to engine-side decode for that
worker's batches, trading speed for exactness.

Workers never import jax; the engine process keeps sole ownership of the
device. On a 1-core host the pool degrades to a single worker and roughly
matches the in-process path; with spare cores the scan work scales out.
"""

from __future__ import annotations

import ctypes
import logging
import multiprocessing as mp
import os
import threading
import time
from multiprocessing import shared_memory

import numpy as np

logger = logging.getLogger(__name__)

# One process-wide thread pool behind every engine's sharded decode:
# shard scans release the GIL inside the native call, so the threads are
# fungible across engines, and a shared pool keeps "many engines in one
# test process" from accumulating idle thread stacks.
_shard_pool = None
_shard_pool_lock = threading.Lock()


def _shard_executor():
    global _shard_pool
    if _shard_pool is None:
        with _shard_pool_lock:
            if _shard_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                _shard_pool = ThreadPoolExecutor(
                    max_workers=max(1, (os.cpu_count() or 2) - 1),
                    thread_name_prefix="swtpu-shard")
    return _shard_pool

_HDR = 8  # int64 header slots in shm_in: [n_msgs, buf_len, ...reserved]


def _shm_arrays(buf, max_msgs: int, channels: int):
    """Carve the output SoA views out of one shared-memory block."""
    b, c = max_msgs, channels
    off = 0

    def take(dtype, shape):
        nonlocal off
        n = int(np.prod(shape)) * np.dtype(dtype).itemsize
        a = np.ndarray(shape, dtype, buffer=buf, offset=off)
        off += n
        return a

    return {
        "rtype": take(np.int32, (b,)),
        "token": take(np.int32, (b,)),
        "ts": take(np.int64, (b,)),
        "values": take(np.float32, (b, c)),
        "chmask": take(np.uint8, (b, c)),
        "aux0": take(np.int32, (b,)),
        "aux1": take(np.int32, (b,)),
        "level": take(np.int32, (b,)),
    }


def _out_bytes(max_msgs: int, channels: int) -> int:
    return max_msgs * (4 + 4 + 8 + 4 * channels + channels + 4 + 4 + 4)


def _worker_main(conn, in_name: str, out_name: str, max_msgs: int,
                 max_bytes: int, channels: int, token_capacity: int) -> None:
    """One decode worker: wire batch in shm_in -> SoA in shm_out.
    Replies ("done", n_ok, collisions, new_tokens, new_names, new_alerts)
    where the new_* lists carry strings interned FOR THE FIRST TIME by this
    batch, in local-id order (the engine extends its translation tables
    from exactly these)."""
    from sitewhere_tpu.ingest.fast_decode import NativeBatchDecoder
    from sitewhere_tpu.native.binding import NativeInterner

    shm_in = shared_memory.SharedMemory(name=in_name)
    shm_out = shared_memory.SharedMemory(name=out_name)
    try:
        hdr = np.ndarray((_HDR,), np.int64, buffer=shm_in.buf)
        offsets = np.ndarray((max_msgs + 1,), np.int64, buffer=shm_in.buf,
                             offset=_HDR * 8)
        data_off = _HDR * 8 + (max_msgs + 1) * 8
        out = _shm_arrays(shm_out.buf, max_msgs, channels)

        tokens = NativeInterner(token_capacity)
        dec = NativeBatchDecoder(tokens, channels)
        n_tok = n_name = n_alert = n_eid = 0

        def tail(interner, since: int) -> list[str]:
            return [interner.token(i) for i in range(since, len(interner))]

        while True:
            msg = conn.recv()
            if msg is None:
                break
            n = int(hdr[0])
            payloads_buf = bytes(shm_in.buf[data_off:data_off + int(hdr[1])])
            # one scanner call over the whole batch, straight into shm
            n_ok, collisions = dec.decode_packed(
                payloads_buf, offsets, n, out["rtype"], out["token"],
                out["ts"], out["values"], out["chmask"], out["aux0"],
                out["aux1"], out["level"])
            new_tokens = tail(tokens, n_tok)
            new_names = tail(dec.names, n_name)
            new_alerts = tail(dec.alert_types, n_alert)
            new_eids = tail(dec.event_ids, n_eid)
            n_tok += len(new_tokens)
            n_name += len(new_names)
            n_alert += len(new_alerts)
            n_eid += len(new_eids)
            conn.send(("done", n_ok, collisions,
                       new_tokens, new_names, new_alerts, new_eids))
    finally:
        shm_in.close()
        shm_out.close()
        conn.close()


class _Worker:
    def __init__(self, idx: int, max_msgs: int, max_bytes: int,
                 channels: int, token_capacity: int, ctx):
        in_bytes = _HDR * 8 + (max_msgs + 1) * 8 + max_bytes
        self.shm_in = shared_memory.SharedMemory(
            create=True, size=in_bytes)
        self.shm_out = shared_memory.SharedMemory(
            create=True, size=_out_bytes(max_msgs, channels))
        self.hdr = np.ndarray((_HDR,), np.int64, buffer=self.shm_in.buf)
        self.offsets = np.ndarray((max_msgs + 1,), np.int64,
                                  buffer=self.shm_in.buf, offset=_HDR * 8)
        self.data_off = _HDR * 8 + (max_msgs + 1) * 8
        self.out = _shm_arrays(self.shm_out.buf, max_msgs, channels)
        self.conn, child = ctx.Pipe()
        self.proc = ctx.Process(
            target=_worker_main,
            args=(child, self.shm_in.name, self.shm_out.name, max_msgs,
                  max_bytes, channels, token_capacity),
            daemon=True)
        self.proc.start()
        child.close()
        # engine-side translation state
        self.tok_map = np.empty(0, np.int32)
        self.alert_map = np.empty(0, np.int32)
        self.eid_map = np.empty(0, np.int32)   # worker alt-id -> engine id
        self.lane_owner: dict[int, int] = {}   # worker lane -> engine lane
        self.elane_owner: dict[int, int] = {}  # engine lane -> worker lane
        self.n_names_seen = 0   # dense worker-local name ids handed out
        self.lane_conflict = False
        self.pending: tuple[list[bytes], str] | None = None

    def close(self) -> None:
        try:
            self.conn.send(None)
        except (BrokenPipeError, OSError):
            pass
        self.proc.join(timeout=5)
        if self.proc.is_alive():
            self.proc.kill()
            self.proc.join(timeout=5)
        self.conn.close()
        for shm in (self.shm_in, self.shm_out):
            shm.close()
            try:
                shm.unlink()
            except FileNotFoundError:
                pass


class ShardedArenaDecoder:
    """In-process sharded arena decode: one wire batch splits across N
    decode workers by payload BYTES (not counts), each worker decoding a
    contiguous payload range into the matching disjoint row range of the
    same :class:`StagingArena` via ``swtpu_shard_decode_arena_pylist``.
    The native scans release the GIL, so shards genuinely parallelize
    across cores; the engine lock (held by the caller) keeps the shared
    interners read-only for the whole call.

    Determinism contract (pinned by tests/test_shard_decode.py): arena
    contents — including interner id assignment — are byte-identical to
    the single-threaded ``NativeBatchDecoder.decode_into`` path. Strings
    not yet in the shared interners go to per-shard OVERLAY tables and
    their uses become patch records; the serial merge interns overlay
    tails in shard order, which IS first-occurrence row order (shards
    are ordered contiguous row ranges, and each overlay assigns local
    ids in first-occurrence order), then applies the patches as
    vectorized scatters. Known divergence: within ONE row, a first-seen
    measurement name whose final lane collides with an already-known
    name's lane applies after the scan (patch order) instead of in key
    order — reachable only under lane aliasing, which the single path
    also mishandles (by aliasing).
    """

    # below this many payloads per shard, thread + merge overhead beats
    # the parallel scan win — the batch decodes single-threaded instead
    min_shard_payloads = 64

    def __init__(self, decoder, n_workers: int):
        if not decoder.has_shard:
            raise RuntimeError("sharded decode entry points unavailable")
        if n_workers < 1:
            raise ValueError("need at least one decode worker")
        self.decoder = decoder
        self.lib = decoder.lib
        self.py_lib = decoder.py_lib
        self.n_workers = n_workers
        self.active_workers = n_workers   # autotuner-adjustable fan-out
        self.last_workers = 1             # shards used by the last batch
        self.sharded_batches = 0
        # span plumbing (ISSUE 10): the engine sets ``tracer`` once and
        # ``current_trace`` per batch (under its lock, which serializes
        # arena decode) so each shard's native scan records a span on
        # the batch's trace — both default off for direct constructors
        self.tracer = None
        self.current_trace: str | None = None
        self._ctxs = [self.lib.swtpu_shard_create(decoder.handle)
                      for _ in range(n_workers)]

    def set_active_workers(self, n: int) -> int:
        """Clamp and apply a new shard fan-out (autotuner hook)."""
        self.active_workers = max(1, min(int(n), self.n_workers))
        return self.active_workers

    # ------------------------------------------------------------- decode
    def decode_into(self, payloads, arena, lo: int,
                    *, binary: bool = False) -> tuple[int, int]:
        """Drop-in for ``NativeBatchDecoder.decode_into`` — same outputs,
        same contract, decoded by up to ``active_workers`` shards."""
        n = len(payloads)
        if lo + n > arena.rows:
            # same guard as the single-threaded contract: short column
            # slices would hand the native scanner pointers it writes past
            raise ValueError(f"{n} payloads exceed arena room "
                             f"{arena.rows - lo}")
        k = min(self.active_workers, n // self.min_shard_payloads)
        if k <= 1 or type(payloads) is not list:
            self.last_workers = 1
            return self.decoder.decode_into(payloads, arena, lo,
                                            binary=binary)
        lens = np.fromiter(map(len, payloads), np.int64, n)
        cum = np.cumsum(lens)
        total = int(cum[-1])
        # contiguous payload ranges cut at ~equal BYTE boundaries: the
        # scan cost tracks bytes, not message counts, and contiguity is
        # what makes shard order == row order (the determinism argument)
        targets = (total * np.arange(1, k)) // k
        cuts = np.searchsorted(cum, targets, side="left") + 1
        bounds = [0]
        for b in cuts:
            b = int(min(b, n))
            if b > bounds[-1]:
                bounds.append(b)
        if bounds[-1] != n:
            bounds.append(n)
        used = len(bounds) - 1
        if used <= 1:
            self.last_workers = 1
            return self.decoder.decode_into(payloads, arena, lo,
                                            binary=binary)
        pool = _shard_executor()
        futs = [
            pool.submit(self._decode_shard, w, payloads, bounds[w],
                        bounds[w + 1] - bounds[w], arena,
                        lo + bounds[w], binary)
            for w in range(1, used)
        ]
        first = self._decode_shard(0, payloads, 0, bounds[1], arena, lo,
                                   binary)
        results = [first] + [f.result() for f in futs]
        if any(r is None for r in results):
            # a shard saw a non-bytes item: redo the whole range through
            # the single path (shards never touched the shared interners,
            # so the retry is side-effect free)
            self.last_workers = 1
            return self.decoder.decode_into(payloads, arena, lo,
                                            binary=binary)
        n_ok = sum(r[0] for r in results)
        collisions = sum(r[1] for r in results)
        ok_drop, extra_coll = self._merge(used, arena, bounds, lo)
        self.last_workers = used
        self.sharded_batches += 1
        return n_ok - ok_drop, collisions + extra_coll

    def _decode_shard(self, w: int, payloads, start: int, cnt: int,
                      arena, row0: int, binary: bool):
        c = ctypes
        collisions = c.c_int32(0)
        t0 = time.perf_counter_ns()
        args = self.decoder.arena_out_args(arena, row0, row0 + cnt,
                                           collisions)
        n_ok = int(self.py_lib.swtpu_shard_decode_arena_pylist(
            self._ctxs[w], payloads, np.int32(start), np.int32(cnt),
            np.int32(self.decoder.channels), *args,
            np.int32(1 if binary else 0)))
        tracer = self.tracer
        if tracer is not None and tracer.enabled:
            tracer.record("ingest.shard_decode", t0,
                          time.perf_counter_ns(),
                          trace_id=self.current_trace, shard=w,
                          payloads=cnt)
        if n_ok < 0:
            return None
        return n_ok, int(collisions.value)

    # -------------------------------------------------------------- merge
    def _merge(self, used: int, arena, bounds, lo: int) -> tuple[int, int]:
        """Interner-tail merge + patch application. Serial, under the
        engine lock. Walks shards in order; each shard's first-seen
        strings intern in local-id order — together, exactly the
        single-threaded first-occurrence order. Patch scatters only
        overwrite cells still holding the matching provisional id
        (-2 - idx): a later occurrence of the key may have replaced it.
        Returns (ok_rows_dropped, extra_lane_collisions)."""
        c = ctypes
        lib = self.lib
        dec = self.decoder
        handles = (dec.tokens.handle, dec.names.handle,
                   dec.alert_types.handle, dec.event_ids.handle)
        channels = dec.channels
        sbuf = c.create_string_buffer(1024)
        ok_drop = 0
        extra_coll = 0

        def ptr(a, t):
            return a.ctypes.data_as(c.POINTER(t))

        for w in range(used):
            ctx = self._ctxs[w]
            row0 = lo + bounds[w]
            maps = []
            for kind in range(4):
                cnt = int(lib.swtpu_shard_new_count(ctx, np.int32(kind)))
                m = np.empty(cnt, np.int32)
                for i in range(cnt):
                    ln = int(lib.swtpu_shard_new_string(
                        ctx, np.int32(kind), np.int32(i), sbuf, 1024))
                    m[i] = int(lib.swtpu_intern(
                        handles[kind], sbuf.raw[:ln], np.int32(ln)))
                maps.append(m)
            for kind in range(4):
                pc = int(lib.swtpu_shard_patch_count(ctx, np.int32(kind)))
                if not pc:
                    continue
                rows = np.empty(pc, np.int32)
                idxs = np.empty(pc, np.int32)
                vals = np.empty(pc, np.float32)
                lib.swtpu_shard_patch_fetch(
                    ctx, np.int32(kind), ptr(rows, c.c_int32),
                    ptr(idxs, c.c_int32), ptr(vals, c.c_float))
                rows = rows + np.int32(row0)
                if kind == 0:      # device tokens
                    fin = maps[kind][idxs]
                    cur = arena.token_id[rows]
                    hit = cur == (-2 - idxs)
                    r, f = rows[hit], fin[hit]
                    arena.token_id[r] = f
                    bad = f < 0
                    if bad.any():
                        # interner capacity exhausted during the merge:
                        # the row becomes a decode failure, like the
                        # direct path's interner-full rejection
                        rb = r[bad]
                        ok_drop += int(np.sum(arena.rtype[rb] >= 0))
                        arena.rtype[rb] = -1
                        arena.token_id[rb] = -1
                elif kind == 1:    # measurement names -> value lanes
                    # idx >= 0: overlay id (map via the merged tail,
                    # collision counted here against the final id);
                    # idx < 0: a known name deferred for key-order
                    # replay, final id rides bit-inverted and its
                    # collision was already counted at scan time
                    direct = idxs < 0
                    fin = np.where(direct, ~idxs,
                                   maps[kind][np.where(direct, 0, idxs)])
                    good = fin >= 0
                    extra_coll += int(np.sum(fin[good & ~direct]
                                             >= channels))
                    f = fin[good]
                    # in-order scatter: repeated (row, lane) pairs keep
                    # the LAST write, matching single-threaded key order
                    arena.values[rows[good], f % channels] = vals[good]
                    arena.vmask[rows[good], f % channels] = 1
                else:              # alert types (aux0) / alternate ids (aux1)
                    fin = maps[kind][idxs]
                    lane = 0 if kind == 2 else 1
                    cur = arena.aux[rows, lane]
                    hit = cur == (-2 - idxs)
                    arena.aux[rows[hit], lane] = np.where(
                        fin[hit] >= 0, fin[hit], -1)
        return ok_drop, extra_coll

    def close(self) -> None:
        for ctx in self._ctxs:
            self.lib.swtpu_shard_destroy(ctx)
        self._ctxs = []


class DecodeWorkerPool:
    """Round-robin pool of decode workers in front of one engine.

    ``submit()`` hands a wire batch to the next worker and returns
    immediately (absorbing that worker's previous batch first if still
    outstanding); ``flush()`` absorbs everything. Ingest summaries come
    back from the absorb step with the same shape as
    ``engine.ingest_json_batch``."""

    def __init__(self, engine, n_workers: int | None = None,
                 max_msgs: int | None = None, max_bytes: int = 1 << 24):
        from sitewhere_tpu.ingest.fast_decode import native_available

        if not native_available():
            raise RuntimeError("native library unavailable")
        if engine.config.strict_channels:
            # the strict contract (reject + roll back a batch that would
            # exceed channel capacity, engine._check_strict_native) cannot
            # be enforced from worker-local interners — a colliding batch
            # would be WAL-logged and staged before the engine could see
            # the collision. Refuse loudly instead of silently degrading.
            raise ValueError(
                "DecodeWorkerPool does not support strict_channels engines;"
                " use the in-process ingest path")
        self.engine = engine
        self.channels = engine.config.channels
        self.n_workers = n_workers or max(1, (os.cpu_count() or 1) - 1)
        self.max_msgs = max_msgs or max(16384, engine.config.batch_capacity)
        self.max_bytes = max_bytes
        ctx = mp.get_context("spawn")   # workers must not inherit jax state
        self.workers = [
            _Worker(i, self.max_msgs, max_bytes, self.channels,
                    engine.config.token_capacity, ctx)
            for i in range(self.n_workers)
        ]
        self._next = 0
        self.summaries: list[dict] = []
        self.fallback_batches = 0

    # ------------------------------------------------------------ engine side
    def _absorb(self, w: _Worker) -> dict | None:
        if w.pending is None:
            return None
        payloads, tenant = w.pending
        w.pending = None
        kind, n_ok, collisions, new_tokens, new_names, new_alerts, \
            new_eids = w.conn.recv()
        assert kind == "done"
        eng = self.engine
        # ---- extend translation tables from first-seen strings ----------
        # Under eng.lock: these interners are shared with REST registration
        # and in-process ingest, which all intern under the same lock.
        with eng.lock:
            if new_tokens:
                w.tok_map = np.concatenate([
                    w.tok_map,
                    np.fromiter((eng.tokens.intern(t) for t in new_tokens),
                                np.int32, len(new_tokens))])
            if new_alerts:
                w.alert_map = np.concatenate([
                    w.alert_map,
                    np.fromiter(
                        (eng.alert_types.intern(t) for t in new_alerts),
                        np.int32, len(new_alerts))])
            if new_eids:
                w.eid_map = np.concatenate([
                    w.eid_map,
                    np.fromiter(
                        (eng.event_ids.intern(t) for t in new_eids),
                        np.int32, len(new_eids))])
            if new_names:
                names_interner = (eng._native_decoder.names
                                  if eng._native_decoder else None)
                for name in new_names:
                    wid = w.n_names_seen   # dense worker-local name id order
                    w.n_names_seen += 1
                    eid = (names_interner.intern(name) if names_interner
                           else eng.channel_map.names.intern(name))
                    wlane, elane = wid % self.channels, eid % self.channels
                    prev = w.lane_owner.get(wlane)
                    if prev is None:
                        # the engine lane must not already belong to a
                        # DIFFERENT worker lane — a non-injective map would
                        # let one lane's scatter clobber the other's
                        # (silent data loss)
                        if w.elane_owner.get(elane, wlane) != wlane:
                            w.lane_conflict = True
                        w.lane_owner[wlane] = elane
                        w.elane_owner[elane] = wlane
                    elif prev != elane:
                        w.lane_conflict = True
        n = len(payloads)
        if w.lane_conflict:
            # ambiguous lane permutation: exactness over speed — decode
            # this worker's batches in-engine from the raw payloads.
            # Surfaced as an engine metric so operators see the pool
            # degrading, not just a log line (VERDICT r3 weak #1)
            self.fallback_batches += 1
            with eng.lock:
                eng.host_counters["worker_fallback_batches"] = \
                    eng.host_counters.get("worker_fallback_batches", 0) + 1
            return eng.ingest_json_batch(payloads, tenant=tenant)
        # ---- translate + stage (numpy gathers only) ---------------------
        from sitewhere_tpu.engine import WAL_JSON
        from sitewhere_tpu.ingest.decoders import JsonDeviceRequestDecoder
        from sitewhere_tpu.ingest.fast_decode import (RT_ALERT,
                                                      RT_MEASUREMENT,
                                                      DecodedArrays)

        # shm views, NOT copies: the engine's staging (arena copy or
        # legacy buffer slices) completes synchronously inside
        # _ingest_decoded below, before this worker can be handed its
        # next batch — so the worker never overwrites a view in use.
        o = w.out
        rtype = o["rtype"][:n]
        token = o["token"][:n]
        gtok = (w.tok_map[np.clip(token, 0, max(0, len(w.tok_map) - 1))]
                if len(w.tok_map) else np.full(n, -1, np.int32))
        gtok = np.where(rtype >= 0, gtok, -1).astype(np.int32)
        # scatter ONLY lanes that carry data (every data-carrying worker
        # lane has a name behind it, hence an entry in lane_owner);
        # unmapped lanes must never overwrite a mapped engine lane
        if all(wl == el for wl, el in w.lane_owner.items()):
            values = o["values"][:n]
            chmask = o["chmask"][:n].astype(bool)
        else:
            wl = np.fromiter(w.lane_owner.keys(), np.int64,
                             len(w.lane_owner))
            el = np.fromiter(w.lane_owner.values(), np.int64,
                             len(w.lane_owner))
            raw_v = o["values"][:n]
            raw_m = o["chmask"][:n].astype(bool)
            values = np.zeros((n, self.channels), np.float32)
            chmask = np.zeros((n, self.channels), bool)
            values[:, el] = raw_v[:, wl]
            chmask[:, el] = raw_m[:, wl]
            # the lane permutation is derived from measurement names only;
            # LOCATION rows carry lat/lon/elev in FIXED lanes 0-2 (see
            # swtpu.cpp scan_location) and other non-measurement rows use
            # raw lanes — keep their lanes untouched
            nonmeas = rtype != RT_MEASUREMENT
            if np.any(nonmeas):
                values[nonmeas] = raw_v[nonmeas]
                chmask[nonmeas] = raw_m[nonmeas]
        aux0 = o["aux0"][:n]
        alert_rows = rtype == RT_ALERT
        if np.any(alert_rows) and len(w.alert_map):
            # in-place alert-type translation on the shm view is safe:
            # this slot is dead until the worker's next batch overwrites it
            aux0[alert_rows] = w.alert_map[
                np.clip(aux0[alert_rows], 0, len(w.alert_map) - 1)]
        aux1 = o["aux1"][:n]
        alt_rows = aux1 >= 0
        if np.any(alt_rows) and len(w.eid_map):
            aux1[alt_rows] = w.eid_map[
                np.clip(aux1[alt_rows], 0, len(w.eid_map) - 1)]
        res = DecodedArrays(
            n_ok=int(np.sum(rtype >= 0)), rtype=rtype, token_id=gtok,
            ts_ms64=o["ts"][:n], values=values, chmask=chmask,
            aux0=aux0, aux1=aux1, level=o["level"][:n],
            collisions=collisions)
        with eng.lock:
            eng._wal_append(WAL_JSON, payloads, tenant)
            # _ingest_decoded routes through the engine's staging arenas
            # when they exist: ONE vectorized shm->arena copy replaces
            # the DecodedArrays copies + HostEventBuffer staging pass.
            # On an SpmdEngine the same seam scatters the shm columns
            # into the stacked per-shard arena lanes instead.
            return eng._ingest_decoded(res, payloads, tenant,
                                       JsonDeviceRequestDecoder())

    def submit(self, payloads: list[bytes], tenant: str = "default") -> None:
        """Queue one wire batch on the next worker (absorbs that worker's
        outstanding batch first, so at most one batch is in flight per
        worker)."""
        w = self.workers[self._next]
        self._next = (self._next + 1) % self.n_workers
        s = self._absorb(w)
        if s is not None:
            self.summaries.append(s)
        n = len(payloads)
        if n > self.max_msgs:
            raise ValueError(f"batch of {n} exceeds max_msgs {self.max_msgs}")
        lens = np.fromiter((len(p) for p in payloads), np.int64, n)
        total = int(lens.sum())
        if total > self.max_bytes:
            raise ValueError(
                f"batch of {total} payload bytes exceeds the pool's "
                f"max_bytes {self.max_bytes}; raise max_bytes or split "
                "the batch")
        self.offsets_fill(w, lens)
        buf = b"".join(payloads)
        w.shm_in.buf[w.data_off:w.data_off + len(buf)] = buf
        w.hdr[0], w.hdr[1] = n, len(buf)
        w.pending = (payloads, tenant)
        w.conn.send(("decode",))

    @staticmethod
    def offsets_fill(w: _Worker, lens: np.ndarray) -> None:
        w.offsets[0] = 0
        np.cumsum(lens, out=w.offsets[1:1 + len(lens)])

    def flush(self) -> list[dict]:
        """Absorb every outstanding batch; returns their summaries."""
        out, self.summaries = self.summaries, []
        for w in self.workers:
            s = self._absorb(w)
            if s is not None:
                out.append(s)
        return out

    def stats(self) -> dict:
        return {
            "n_workers": self.n_workers,
            "fallback_batches": self.fallback_batches,
            "lane_conflicts": sum(1 for w in self.workers if w.lane_conflict),
        }

    def close(self) -> None:
        self.flush()
        for w in self.workers:
            w.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
