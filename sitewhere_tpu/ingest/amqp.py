"""Native AMQP 0-9-1: wire codec, asyncio client, embedded broker, receiver.

The reference ingests from RabbitMQ by declaring a queue and consuming it
with auto-ack (sources/rabbitmq/RabbitMqInboundEventReceiver.java:120-140 —
``queueDeclare(queue, durable, false, false, null)`` then
``basicConsume(queue, true, consumer)``), with scheduled reconnect on
connection loss (lines 60-75), and publishes outbound events to a per-tenant
*topic* exchange (connectors/rabbitmq/RabbitMqOutboundConnector.java:96-97,
233 — ``exchangeDeclare(exchange, "topic")`` + ``basicPublish(exchange,
topic, json)``). No AMQP library ships in this image, so the protocol subset
needed for those two paths is implemented here: connection negotiation with
PLAIN auth, channels, exchange.declare (direct/topic/fanout), queue.declare,
queue.bind with AMQP topic wildcards (``*`` one word, ``#`` zero or more),
basic.publish / basic.consume / basic.deliver with auto-ack, and an embedded
broker used by tests and the load generator.

Legacy-compat receiver: this path submits one payload at a time through
``InboundEventSource`` (per-event decode + engine call). New high-rate
device transports should front the batched persistent-connection edge
(``ingest/wire_edge.py`` — MQTT/SWP/websocket frames into staging-arena
arrival windows); broker sources that must stay on this receiver can
inherit the sources manager's shared ``WireBatcher`` when their decoder
is batchable.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from collections import deque
from typing import Any, Callable

from sitewhere_tpu.ingest.sources import InboundEventReceiver

logger = logging.getLogger(__name__)

PROTOCOL_HEADER = b"AMQP\x00\x00\x09\x01"
FRAME_METHOD, FRAME_HEADER, FRAME_BODY, FRAME_HEARTBEAT = 1, 2, 3, 8
FRAME_END = 0xCE

# (class, method) ids used by the subset
CONN_START, CONN_START_OK = (10, 10), (10, 11)
CONN_TUNE, CONN_TUNE_OK = (10, 30), (10, 31)
CONN_OPEN, CONN_OPEN_OK = (10, 40), (10, 41)
CONN_CLOSE, CONN_CLOSE_OK = (10, 50), (10, 51)
CH_OPEN, CH_OPEN_OK = (20, 10), (20, 11)
CH_CLOSE, CH_CLOSE_OK = (20, 40), (20, 41)
EX_DECLARE, EX_DECLARE_OK = (40, 10), (40, 11)
Q_DECLARE, Q_DECLARE_OK = (50, 10), (50, 11)
Q_BIND, Q_BIND_OK = (50, 20), (50, 21)
BASIC_CONSUME, BASIC_CONSUME_OK = (60, 20), (60, 21)
BASIC_PUBLISH, BASIC_DELIVER = (60, 40), (60, 60)


# --- argument codec ----------------------------------------------------------


class ArgWriter:
    """Packs AMQP method arguments (subset: octet/short/long/longlong/
    shortstr/longstr/table/bits)."""

    def __init__(self) -> None:
        self.buf = bytearray()
        self._bits: list[bool] = []

    def _flush_bits(self) -> None:
        while self._bits:
            chunk, self._bits = self._bits[:8], self._bits[8:]
            self.buf.append(sum(1 << i for i, b in enumerate(chunk) if b))

    def octet(self, v: int) -> "ArgWriter":
        self._flush_bits()
        self.buf.append(v & 0xFF)
        return self

    def short(self, v: int) -> "ArgWriter":
        self._flush_bits()
        self.buf += v.to_bytes(2, "big")
        return self

    def long(self, v: int) -> "ArgWriter":
        self._flush_bits()
        self.buf += v.to_bytes(4, "big")
        return self

    def longlong(self, v: int) -> "ArgWriter":
        self._flush_bits()
        self.buf += v.to_bytes(8, "big")
        return self

    def shortstr(self, s: str) -> "ArgWriter":
        self._flush_bits()
        b = s.encode()
        self.buf.append(len(b))
        self.buf += b
        return self

    def longstr(self, b: bytes) -> "ArgWriter":
        self._flush_bits()
        self.buf += len(b).to_bytes(4, "big") + b
        return self

    def table(self, t: dict[str, str] | None = None) -> "ArgWriter":
        self._flush_bits()
        body = bytearray()
        for k, v in (t or {}).items():
            kb, vb = k.encode(), v.encode()
            body.append(len(kb))
            body += kb + b"S" + len(vb).to_bytes(4, "big") + vb
        self.buf += len(body).to_bytes(4, "big") + body
        return self

    def bit(self, v: bool) -> "ArgWriter":
        self._bits.append(bool(v))
        return self

    def done(self) -> bytes:
        self._flush_bits()
        return bytes(self.buf)


class ArgReader:
    def __init__(self, data: bytes):
        self.data, self.off = data, 0

    def _take(self, n: int) -> bytes:
        b = self.data[self.off: self.off + n]
        self.off += n
        return b

    def octet(self) -> int:
        return self._take(1)[0]

    def short(self) -> int:
        return int.from_bytes(self._take(2), "big")

    def long(self) -> int:
        return int.from_bytes(self._take(4), "big")

    def longlong(self) -> int:
        return int.from_bytes(self._take(8), "big")

    def shortstr(self) -> str:
        return self._take(self.octet()).decode()

    def longstr(self) -> bytes:
        return self._take(self.long())

    def table(self) -> dict[str, str]:
        end = self.long() + self.off
        out: dict[str, str] = {}
        while self.off < end:
            key = self.shortstr()
            kind = self._take(1)
            if kind == b"S":
                out[key] = self.longstr().decode()
            elif kind == b"t":
                out[key] = str(bool(self.octet()))
            else:  # unknown field kind: bail out of the table conservatively
                self.off = end
                break
        return out

    def bits(self, n: int = 1) -> list[bool]:
        v = self.octet()
        return [bool(v >> i & 1) for i in range(n)]


def encode_method(channel: int, cm: tuple[int, int], args: bytes) -> bytes:
    payload = cm[0].to_bytes(2, "big") + cm[1].to_bytes(2, "big") + args
    return (bytes([FRAME_METHOD]) + channel.to_bytes(2, "big")
            + len(payload).to_bytes(4, "big") + payload + bytes([FRAME_END]))


def encode_content(channel: int, body: bytes, class_id: int = 60) -> bytes:
    """Content header (no properties) + one body frame."""
    hdr = (class_id.to_bytes(2, "big") + b"\x00\x00"
           + len(body).to_bytes(8, "big") + b"\x00\x00")
    out = (bytes([FRAME_HEADER]) + channel.to_bytes(2, "big")
           + len(hdr).to_bytes(4, "big") + hdr + bytes([FRAME_END]))
    if body:
        out += (bytes([FRAME_BODY]) + channel.to_bytes(2, "big")
                + len(body).to_bytes(4, "big") + body + bytes([FRAME_END]))
    return out


async def read_frame(reader: asyncio.StreamReader) -> tuple[int, int, bytes]:
    head = await reader.readexactly(7)
    ftype = head[0]
    channel = int.from_bytes(head[1:3], "big")
    size = int.from_bytes(head[3:7], "big")
    payload = await reader.readexactly(size)
    (end,) = await reader.readexactly(1)
    if end != FRAME_END:
        raise ValueError("missing AMQP frame-end octet")
    return ftype, channel, payload


def topic_key_matches(pattern: str, key: str) -> bool:
    """AMQP topic-exchange match: ``.``-separated words, ``*`` = exactly one
    word, ``#`` = zero or more words."""
    pw, kw = pattern.split("."), key.split(".")

    def match(pi: int, ki: int) -> bool:
        while pi < len(pw):
            seg = pw[pi]
            if seg == "#":
                if pi == len(pw) - 1:
                    return True
                return any(match(pi + 1, j) for j in range(ki, len(kw) + 1))
            if ki >= len(kw) or (seg != "*" and seg != kw[ki]):
                return False
            pi += 1
            ki += 1
        return ki == len(kw)

    return match(0, 0)


# --- broker ------------------------------------------------------------------


class _Queue:
    def __init__(self, name: str):
        self.name = name
        self.pending: deque[bytes] = deque()
        # (writer, channel, consumer_tag) round-robin
        self.consumers: deque[tuple[asyncio.StreamWriter, int, str]] = deque()


class AmqpBroker:
    """Embedded AMQP 0-9-1 broker: direct/topic/fanout exchanges, queue
    bindings, round-robin delivery to auto-ack consumers. Stands in for the
    external RabbitMQ the reference assumes, the same way ingest/mqtt.py's
    MqttBroker stands in for an MQTT broker."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host, self.port = host, port
        self._server: asyncio.AbstractServer | None = None
        self.exchanges: dict[str, str] = {"": "direct", "amq.topic": "topic"}
        self.queues: dict[str, _Queue] = {}
        self.bindings: list[tuple[str, str, str]] = []  # (exchange, queue, key)
        self._writers: set[asyncio.StreamWriter] = set()
        self._tags = itertools.count(1)

    @property
    def bound_port(self) -> int:
        assert self._server is not None and self._server.sockets
        return self._server.sockets[0].getsockname()[1]

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)

    async def stop(self) -> None:
        for w in list(self._writers):
            w.close()
        self._writers.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    def _route(self, exchange: str, key: str) -> list[_Queue]:
        kind = self.exchanges.get(exchange, "direct")
        if exchange == "":
            q = self.queues.get(key)
            return [q] if q is not None else []
        out = []
        for ex, qname, pattern in self.bindings:
            if ex != exchange:
                continue
            ok = (kind == "fanout" or (kind == "direct" and pattern == key)
                  or (kind == "topic" and topic_key_matches(pattern, key)))
            if ok and qname in self.queues:
                out.append(self.queues[qname])
        return out

    async def _deliver(self, q: _Queue, body: bytes, exchange: str, key: str) -> None:
        while q.consumers:
            writer, channel, tag = q.consumers[0]
            if writer.is_closing():
                q.consumers.popleft()
                continue
            q.consumers.rotate(-1)
            args = (ArgWriter().shortstr(tag).longlong(1).bit(False)
                    .shortstr(exchange).shortstr(key).done())
            try:
                writer.write(encode_method(channel, BASIC_DELIVER, args)
                             + encode_content(channel, body))
                await writer.drain()
                return
            except ConnectionError:
                q.consumers.popleft()
        q.pending.append(body)

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        self._writers.add(writer)
        # publish state machine: after basic.publish we expect header + body
        pub: dict[int, tuple[str, str, int, bytearray]] = {}
        try:
            if await reader.readexactly(8) != PROTOCOL_HEADER:
                writer.close()
                return
            writer.write(encode_method(
                0, CONN_START,
                ArgWriter().octet(0).octet(9).table()
                .longstr(b"PLAIN").longstr(b"en_US").done()))
            await writer.drain()
            while True:
                ftype, channel, payload = await read_frame(reader)
                if ftype == FRAME_HEARTBEAT:
                    continue
                if ftype == FRAME_HEADER:
                    ex, key, _, acc = pub[channel]
                    size = int.from_bytes(payload[4:12], "big")
                    pub[channel] = (ex, key, size, acc)
                    if size == 0:
                        await self._publish(channel, pub)
                    continue
                if ftype == FRAME_BODY:
                    ex, key, size, acc = pub[channel]
                    acc += payload
                    if len(acc) >= size:
                        await self._publish(channel, pub)
                    continue
                r = ArgReader(payload)
                cm = (r.short(), r.short())
                if cm == CONN_START_OK:
                    writer.write(encode_method(
                        0, CONN_TUNE,
                        ArgWriter().short(2047).long(131072).short(0).done()))
                elif cm == CONN_TUNE_OK:
                    pass
                elif cm == CONN_OPEN:
                    writer.write(encode_method(0, CONN_OPEN_OK,
                                               ArgWriter().shortstr("").done()))
                elif cm == CONN_CLOSE:
                    writer.write(encode_method(0, CONN_CLOSE_OK, b""))
                    await writer.drain()
                    break
                elif cm == CH_OPEN:
                    writer.write(encode_method(channel, CH_OPEN_OK,
                                               ArgWriter().longstr(b"").done()))
                elif cm == CH_CLOSE:
                    writer.write(encode_method(channel, CH_CLOSE_OK, b""))
                elif cm == EX_DECLARE:
                    r.short()  # reserved
                    name, kind = r.shortstr(), r.shortstr()
                    self.exchanges[name] = kind or "direct"
                    writer.write(encode_method(channel, EX_DECLARE_OK, b""))
                elif cm == Q_DECLARE:
                    r.short()
                    name = r.shortstr()
                    q = self.queues.setdefault(name, _Queue(name))
                    writer.write(encode_method(
                        channel, Q_DECLARE_OK,
                        ArgWriter().shortstr(name).long(len(q.pending))
                        .long(len(q.consumers)).done()))
                elif cm == Q_BIND:
                    r.short()
                    qname, ex, key = r.shortstr(), r.shortstr(), r.shortstr()
                    self.queues.setdefault(qname, _Queue(qname))
                    self.bindings.append((ex, qname, key))
                    writer.write(encode_method(channel, Q_BIND_OK, b""))
                elif cm == BASIC_CONSUME:
                    r.short()
                    qname = r.shortstr()
                    tag = r.shortstr() or f"ctag-{next(self._tags)}"
                    q = self.queues.setdefault(qname, _Queue(qname))
                    q.consumers.append((writer, channel, tag))
                    writer.write(encode_method(channel, BASIC_CONSUME_OK,
                                               ArgWriter().shortstr(tag).done()))
                    await writer.drain()
                    while q.pending:
                        await self._deliver(q, q.pending.popleft(), "", qname)
                elif cm == BASIC_PUBLISH:
                    r.short()
                    ex, key = r.shortstr(), r.shortstr()
                    pub[channel] = (ex, key, -1, bytearray())
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError, ValueError):
            pass
        finally:
            self._writers.discard(writer)
            for q in self.queues.values():
                q.consumers = deque(c for c in q.consumers if c[0] is not writer)
            writer.close()

    async def _publish(self, channel: int, pub: dict) -> None:
        ex, key, _, acc = pub.pop(channel)
        body = bytes(acc)
        for q in self._route(ex, key):
            await self._deliver(q, body, ex, key)


# --- client ------------------------------------------------------------------


class AmqpClient:
    """Minimal asyncio AMQP 0-9-1 client: one connection, one channel,
    auto-ack consumption (the exact subset the reference's receiver and
    connector use)."""

    def __init__(self, host: str, port: int, username: str = "guest",
                 password: str = "guest", vhost: str = "/"):
        self.host, self.port = host, port
        self.username, self.password, self.vhost = username, password, vhost
        self.on_message: Callable[[str, str, bytes], Any] | None = None
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._task: asyncio.Task | None = None
        self._replies: deque[asyncio.Future] = deque()

    async def connect(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(self.host, self.port)
        self._writer.write(PROTOCOL_HEADER)
        await self._writer.drain()
        ftype, _, payload = await read_frame(self._reader)
        r = ArgReader(payload)
        assert (r.short(), r.short()) == CONN_START, "expected connection.start"
        sasl = b"\x00" + self.username.encode() + b"\x00" + self.password.encode()
        self._writer.write(encode_method(
            0, CONN_START_OK,
            ArgWriter().table().shortstr("PLAIN").longstr(sasl)
            .shortstr("en_US").done()))
        _, _, payload = await read_frame(self._reader)
        r = ArgReader(payload)
        assert (r.short(), r.short()) == CONN_TUNE, "expected connection.tune"
        self._writer.write(encode_method(
            0, CONN_TUNE_OK, ArgWriter().short(2047).long(131072).short(0).done()))
        self._writer.write(encode_method(
            0, CONN_OPEN, ArgWriter().shortstr(self.vhost).shortstr("").bit(False).done()))
        _, _, payload = await read_frame(self._reader)
        r = ArgReader(payload)
        assert (r.short(), r.short()) == CONN_OPEN_OK, "expected connection.open-ok"
        await self._rpc(CH_OPEN, ArgWriter().shortstr("").done(), start_loop=True)

    async def _rpc(self, cm: tuple[int, int], args: bytes,
                   start_loop: bool = False) -> bytes:
        fut = asyncio.get_running_loop().create_future()
        self._replies.append(fut)
        self._writer.write(encode_method(1, cm, args))
        await self._writer.drain()
        if start_loop:
            self._task = asyncio.create_task(self._read_loop())
        return await asyncio.wait_for(fut, 10)

    async def _read_loop(self) -> None:
        deliver: tuple[str, str] | None = None
        size, acc = -1, bytearray()
        try:
            while True:
                ftype, _, payload = await read_frame(self._reader)
                if ftype == FRAME_METHOD:
                    r = ArgReader(payload)
                    cm = (r.short(), r.short())
                    if cm == BASIC_DELIVER:
                        r.shortstr()   # consumer tag
                        r.longlong()   # delivery tag
                        r.bits()       # redelivered
                        deliver = (r.shortstr(), r.shortstr())
                        size, acc = -1, bytearray()
                    elif self._replies:
                        fut = self._replies.popleft()
                        if not fut.done():
                            fut.set_result(payload)
                elif ftype == FRAME_HEADER and deliver is not None:
                    size = int.from_bytes(payload[4:12], "big")
                    if size == 0:
                        await self._dispatch(deliver, b"")
                        deliver = None
                elif ftype == FRAME_BODY and deliver is not None:
                    acc += payload
                    if len(acc) >= size:
                        await self._dispatch(deliver, bytes(acc))
                        deliver = None
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.CancelledError):
            pass

    async def _dispatch(self, deliver: tuple[str, str], body: bytes) -> None:
        if self.on_message is not None:
            res = self.on_message(deliver[0], deliver[1], body)
            if asyncio.iscoroutine(res):
                await res

    async def declare_exchange(self, name: str, kind: str = "topic") -> None:
        await self._rpc(EX_DECLARE,
                        ArgWriter().short(0).shortstr(name).shortstr(kind)
                        .bit(False).bit(True).bit(False).bit(False).bit(False)
                        .table().done())

    async def declare_queue(self, name: str, durable: bool = False) -> None:
        await self._rpc(Q_DECLARE,
                        ArgWriter().short(0).shortstr(name).bit(False)
                        .bit(durable).bit(False).bit(False).bit(False)
                        .table().done())

    async def bind_queue(self, queue: str, exchange: str, routing_key: str) -> None:
        await self._rpc(Q_BIND,
                        ArgWriter().short(0).shortstr(queue).shortstr(exchange)
                        .shortstr(routing_key).bit(False).table().done())

    async def consume(self, queue: str) -> None:
        await self._rpc(BASIC_CONSUME,
                        ArgWriter().short(0).shortstr(queue).shortstr("")
                        .bit(False).bit(True).bit(False).bit(False)
                        .table().done())

    async def publish(self, exchange: str, routing_key: str, body: bytes) -> None:
        args = (ArgWriter().short(0).shortstr(exchange).shortstr(routing_key)
                .bit(False).bit(False).done())
        self._writer.write(encode_method(1, BASIC_PUBLISH, args)
                           + encode_content(1, body))
        await self._writer.drain()

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
        if self._writer is not None:
            try:
                self._writer.write(encode_method(
                    0, CONN_CLOSE,
                    ArgWriter().short(200).shortstr("bye").short(0).short(0).done()))
                await self._writer.drain()
            except ConnectionError:
                pass
            self._writer.close()
            self._writer = None


# --- receiver ----------------------------------------------------------------


class RabbitMqEventReceiver(InboundEventReceiver):
    """Declare a queue and consume it with auto-ack, reconnecting on loss
    (reference: sources/rabbitmq/RabbitMqInboundEventReceiver.java:60-140)."""

    def __init__(self, host: str, port: int, queue: str = "sitewhere.input",
                 durable: bool = False, username: str = "guest",
                 password: str = "guest", reconnect_interval_s: float = 5.0):
        super().__init__(f"rabbitmq:{queue}")
        self.host, self.port = host, port
        self.queue, self.durable = queue, durable
        self.username, self.password = username, password
        self.reconnect_interval_s = reconnect_interval_s
        self.client: AmqpClient | None = None
        self._reconnect_task: asyncio.Task | None = None

    async def _connect(self) -> None:
        self.client = AmqpClient(self.host, self.port, self.username, self.password)
        self.client.on_message = lambda ex, key, body: self.submit(
            body, {"exchange": ex, "routing_key": key})
        await self.client.connect()
        await self.client.declare_queue(self.queue, self.durable)
        await self.client.consume(self.queue)

    async def on_start(self) -> None:
        try:
            await self._connect()
        except (OSError, ConnectionError):
            logger.info("rabbitmq receiver: connect failed, scheduling reconnect")
            self._reconnect_task = asyncio.create_task(self._reconnect_loop())

    async def _reconnect_loop(self) -> None:
        while True:
            await asyncio.sleep(self.reconnect_interval_s)
            try:
                await self._connect()
                return
            except (OSError, ConnectionError):
                continue

    async def on_stop(self) -> None:
        if self._reconnect_task is not None:
            self._reconnect_task.cancel()
        if self.client is not None:
            await self.client.close()
