"""Native batch JSON decoder: list-of-payloads -> SoA arrays in one C call.

This is the performance path for the ingest edge (SURVEY.md §3.2 hot loop
"decode" — the reference runs Jackson per message on the JVM). Payloads are
concatenated into one buffer, the C++ scanner fills numpy arrays directly,
and device tokens / measurement names / alert types come back as interned
int32 ids ready for EventBatch packing. Falls back to the pure-Python
JsonDeviceRequestDecoder when the native library is unavailable.
"""

from __future__ import annotations

import ctypes
from typing import NamedTuple

import numpy as np

from sitewhere_tpu.native.binding import (NativeInterner, load_library,
                                          load_py_library)

# native rtype codes (swtpu.cpp ReqType) -> core EventType / registration
RT_REGISTER = 0
RT_MEASUREMENT = 1
RT_LOCATION = 2
RT_ALERT = 3
RT_STATE_CHANGE = 4
RT_ACK = 5
RT_MAP = 6   # MapDevice envelopes take the host slow path (like REGISTER)

# map native rtype -> core EventType ordinal (EventType in core/types.py)
RTYPE_TO_ETYPE = np.full(8, -1, np.int32)
RTYPE_TO_ETYPE[RT_MEASUREMENT] = 0
RTYPE_TO_ETYPE[RT_LOCATION] = 1
RTYPE_TO_ETYPE[RT_ALERT] = 2
RTYPE_TO_ETYPE[RT_ACK] = 4
RTYPE_TO_ETYPE[RT_STATE_CHANGE] = 5


class DecodedArrays(NamedTuple):
    n_ok: int
    rtype: np.ndarray      # int32[N] native request type (-1 = decode failed)
    token_id: np.ndarray   # int32[N]
    ts_ms64: np.ndarray    # int64[N] epoch ms (-1 = absent)
    values: np.ndarray     # float32[N, C]
    chmask: np.ndarray     # bool[N, C]
    aux0: np.ndarray       # int32[N] alert-type id
    aux1: np.ndarray       # int32[N] alternate-id (event-id interner; -1 none)
    level: np.ndarray      # int32[N] alert level
    collisions: int


class NativeBatchDecoder:
    """Holds the C++ decoder + its interners. The token interner is shared
    with the engine (ids must be the engine's ids); the event-id interner
    (alternate/correlation ids, the aux1 lane) is decoder-owned and the
    engine ADOPTS it as ``event_ids`` so the batch path and the
    per-request path assign the same ids."""

    def __init__(self, token_interner: NativeInterner, channels: int,
                 name_capacity: int = 1 << 20, alert_capacity: int = 1 << 16,
                 event_capacity: int = 1 << 22):
        self.lib = load_library()
        if self.lib is None:
            raise RuntimeError("native library unavailable")
        self.tokens = token_interner
        self.channels = channels
        self.handle = self.lib.swtpu_decoder_create(
            token_interner.handle, name_capacity, alert_capacity,
            event_capacity
        )
        self.names = NativeInterner(
            name_capacity, self.lib, self.lib.swtpu_decoder_names(self.handle)
        )
        self.alert_types = NativeInterner(
            alert_capacity, self.lib, self.lib.swtpu_decoder_alert_types(self.handle)
        )
        self.event_ids = NativeInterner(
            event_capacity, self.lib,
            self.lib.swtpu_decoder_event_ids(self.handle)
        )
        # zero-copy list[bytes] entry point (libswtpu_py.so): skips the
        # b"".join + per-payload length scan + offsets cumsum the packed
        # ABI makes Python pay per batch (~1ms of a 16k batch on the
        # 1-core host). None -> packed path.
        self.py_lib = load_py_library()

    def decode(self, payloads: list[bytes]) -> DecodedArrays:
        """Batched JSON DeviceRequest decode. No thread may mutate
        ``payloads`` until the call returns (the zero-copy path scans
        the payload buffers in place)."""
        return self._decode(payloads, binary=False)

    def decode_binary(self, payloads: list[bytes]) -> DecodedArrays:
        """Batched flat-binary decode (the "protobuf" ingest slot; wire
        format of ingest/decoders.py encode_binary_request). Same
        no-concurrent-mutation contract as :meth:`decode`."""
        return self._decode(payloads, binary=True)

    def _decode_pylist(self, payloads: list[bytes],
                       binary: bool) -> "DecodedArrays | None":
        """List-direct decode; None = not eligible (fall back packed)."""
        if self.py_lib is None or type(payloads) is not list:
            return None
        n = len(payloads)
        c = self.channels
        rtype = np.empty(n, np.int32)
        token = np.empty(n, np.int32)
        ts = np.empty(n, np.int64)
        values = np.empty((n, c), np.float32)
        chmask = np.empty((n, c), np.uint8)
        aux0 = np.empty(n, np.int32)
        aux1 = np.empty(n, np.int32)
        level = np.empty(n, np.int32)
        collisions = ctypes.c_int32(0)

        def ptr(a, t):
            return a.ctypes.data_as(ctypes.POINTER(t))

        n_ok = int(self.py_lib.swtpu_decode_pylist(
            self.handle, payloads, np.int32(n), np.int32(c),
            ptr(rtype, ctypes.c_int32), ptr(token, ctypes.c_int32),
            ptr(ts, ctypes.c_int64), ptr(values, ctypes.c_float),
            ptr(chmask, ctypes.c_uint8), ptr(aux0, ctypes.c_int32),
            ptr(aux1, ctypes.c_int32),
            ptr(level, ctypes.c_int32), ctypes.byref(collisions),
            np.int32(1 if binary else 0)))
        if n_ok < 0:
            return None   # non-bytes item: packed path handles/raises
        return DecodedArrays(
            n_ok=n_ok, rtype=rtype, token_id=token, ts_ms64=ts,
            values=values, chmask=chmask.view(bool), aux0=aux0, aux1=aux1,
            level=level, collisions=int(collisions.value))

    def decode_packed(self, buf, offsets: np.ndarray, n: int,
                      rtype: np.ndarray, token: np.ndarray, ts: np.ndarray,
                      values: np.ndarray, chmask: np.ndarray,
                      aux0: np.ndarray, aux1: np.ndarray, level: np.ndarray,
                      *, binary: bool = False) -> tuple[int, int]:
        """One scanner call over an already-concatenated wire batch
        (``offsets`` int64[>=n+1]; output arrays sized >= n rows). THE
        single marshalling site for swtpu_decode_*_batch — the worker
        pool's shared-memory views and the bench's preallocated arrays
        go through here too, so a signature change has one home.
        Returns (n_ok, channel_collisions)."""
        collisions = ctypes.c_int32(0)

        def ptr(a, t):
            return a.ctypes.data_as(ctypes.POINTER(t))

        fn = (self.lib.swtpu_decode_binary_batch if binary
              else self.lib.swtpu_decode_batch)
        n_ok = int(fn(
            self.handle, buf, ptr(offsets, ctypes.c_int64),
            np.int32(n), np.int32(self.channels),
            ptr(rtype, ctypes.c_int32), ptr(token, ctypes.c_int32),
            ptr(ts, ctypes.c_int64),
            ptr(values, ctypes.c_float), ptr(chmask, ctypes.c_uint8),
            ptr(aux0, ctypes.c_int32), ptr(aux1, ctypes.c_int32),
            ptr(level, ctypes.c_int32),
            ctypes.byref(collisions),
        ))
        return n_ok, int(collisions.value)

    @property
    def has_arena(self) -> bool:
        """Arena-fill entry points present in the loaded libraries."""
        return bool(getattr(self.lib, "_swtpu_has_arena", False))

    @property
    def has_shard(self) -> bool:
        """Sharded (multi-worker) arena-decode entry points present in
        BOTH libraries (the ShardCtx ABI lives in libswtpu.so, the
        ranged list decode in libswtpu_py.so)."""
        return bool(getattr(self.lib, "_swtpu_has_shard", False)
                    and self.py_lib is not None
                    and getattr(self.py_lib, "_swtpu_has_shard", False))

    @staticmethod
    def arena_out_args(arena, lo: int, hi: int, collisions):
        """The output-pointer argument tail shared by the arena and
        shard decode entry points: every output aims at the arena's own
        column slices for rows [lo, hi), with the aux lanes strided."""
        c = ctypes

        def ptr(a, t):
            return a.ctypes.data_as(c.POINTER(t))

        stride = c.c_int64(arena.aux.shape[1])
        return (
            ptr(arena.rtype[lo:hi], c.c_int32),
            ptr(arena.token_id[lo:hi], c.c_int32),
            ptr(arena.ts64[lo:hi], c.c_int64),
            ptr(arena.values[lo:hi], c.c_float),
            ptr(arena.vmask[lo:hi], c.c_uint8),
            ptr(arena.aux[lo:hi], c.c_int32), stride,
            ptr(arena.aux[lo:hi, 1:], c.c_int32), stride,
            ptr(arena.level[lo:hi], c.c_int32),
            c.byref(collisions),
        )

    def decode_into(self, payloads: list[bytes], arena, lo: int,
                    *, binary: bool = False) -> tuple[int, int]:
        """Decode ``payloads`` straight into ``arena`` rows
        [lo, lo + len(payloads)): the scanner's outputs are the arena's
        own column slices (zero-copy staging; the aux[:, 0] / aux[:, 1]
        lanes are written strided in place). Same no-concurrent-mutation
        contract as :meth:`decode`. Returns (n_ok, channel_collisions)."""
        n = len(payloads)
        hi = lo + n
        if hi > arena.rows:
            raise ValueError(f"{n} payloads exceed arena room "
                             f"{arena.rows - lo}")
        c = ctypes
        collisions = c.c_int32(0)

        def ptr(a, t):
            return a.ctypes.data_as(c.POINTER(t))

        args = self.arena_out_args(arena, lo, hi, collisions) \
            + (np.int32(1 if binary else 0),)
        if (self.py_lib is not None and type(payloads) is list
                and getattr(self.py_lib, "_swtpu_has_arena", False)):
            n_ok = int(self.py_lib.swtpu_decode_arena_pylist(
                self.handle, payloads, np.int32(n),
                np.int32(self.channels), *args))
            if n_ok >= 0:
                return n_ok, int(collisions.value)
        # packed fallback (also covers non-list iterables of bytes)
        payloads = list(payloads)
        buf = b"".join(payloads)
        offsets = np.zeros(n + 1, np.int64)
        np.cumsum(np.fromiter(map(len, payloads), np.int64, n),
                  out=offsets[1:])
        n_ok = int(self.lib.swtpu_decode_arena_batch(
            self.handle, buf, ptr(offsets, c.c_int64), np.int32(n),
            np.int32(self.channels), *args))
        return n_ok, int(collisions.value)

    def _decode(self, payloads: list[bytes], binary: bool) -> DecodedArrays:
        fast = self._decode_pylist(payloads, binary=binary)
        if fast is not None:
            return fast
        n = len(payloads)
        c = self.channels
        buf = b"".join(payloads)
        offsets = np.zeros(n + 1, np.int64)
        # map(len) iterates at C level; fromiter keeps cumsum on the fast
        # ndarray path (a list argument routes numpy through the boxed
        # _wrapit fallback — measured ~20% of the non-scanner decode
        # overhead at 16k-payload batches)
        np.cumsum(np.fromiter(map(len, payloads), np.int64, n),
                  out=offsets[1:])
        rtype = np.empty(n, np.int32)
        token = np.empty(n, np.int32)
        ts = np.empty(n, np.int64)
        values = np.empty((n, c), np.float32)
        chmask = np.empty((n, c), np.uint8)
        aux0 = np.empty(n, np.int32)
        aux1 = np.empty(n, np.int32)
        level = np.empty(n, np.int32)
        n_ok, collisions = self.decode_packed(
            buf, offsets, n, rtype, token, ts, values, chmask, aux0, aux1,
            level, binary=binary)
        return DecodedArrays(
            n_ok=n_ok, rtype=rtype, token_id=token, ts_ms64=ts,
            values=values, chmask=chmask.view(bool), aux0=aux0, aux1=aux1,
            level=level, collisions=collisions,
        )


def native_available() -> bool:
    return load_library() is not None
