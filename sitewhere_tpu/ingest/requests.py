"""Host-side decoded device requests — the boundary record between protocol
receivers and the TPU batcher.

Mirrors the reference's ``DeviceRequest`` JSON envelope
(service-event-sources test fixture EventsHelper.java:55-80 builds
``{"deviceToken": ..., "type": "DeviceMeasurement", "request": {...}}``; the
decoder maps it via JsonDeviceRequestMarshaler in
sources/decoder/json/JsonDeviceRequestDecoder.java). Decoders produce these;
the batcher (ingest/batcher.py) interns tokens and packs them into
``EventBatch`` arrays.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any

from sitewhere_tpu.core.types import AlertLevel, EventType


class RequestType(enum.Enum):
    """Device request envelope types (reference: DeviceRequest.Type)."""

    REGISTER_DEVICE = "RegisterDevice"
    DEVICE_MEASUREMENT = "DeviceMeasurement"
    DEVICE_LOCATION = "DeviceLocation"
    DEVICE_ALERT = "DeviceAlert"
    DEVICE_STATE_CHANGE = "DeviceStateChange"
    ACKNOWLEDGE = "Acknowledge"          # command response
    DEVICE_STREAM = "DeviceStream"
    DEVICE_STREAM_DATA = "DeviceStreamData"
    SEND_DEVICE_STREAM_DATA = "SendDeviceStreamData"
    MAP_DEVICE = "MapDevice"             # nested-device mapping


# aliases accepted on the wire (the reference models evolved names)
_TYPE_ALIASES = {
    "DeviceMeasurements": RequestType.DEVICE_MEASUREMENT,
    "RegisterDevice": RequestType.REGISTER_DEVICE,
    "DeviceCommandResponse": RequestType.ACKNOWLEDGE,
}


def parse_request_type(raw: str) -> RequestType:
    alias = _TYPE_ALIASES.get(raw)
    if alias is not None:
        return alias
    return RequestType(raw)


@dataclasses.dataclass
class DecodedRequest:
    """One decoded device request. ``values`` layout follows EventType
    conventions (core/types.py); registration/stream requests carry their
    payload in ``extras``."""

    type: RequestType
    device_token: str
    tenant: str = "default"
    event_ts_ms: int | None = None       # absolute unix ms (None = now);
                                         # the engine converts to its int32
                                         # epoch-relative clock when staging
    # measurement: {name: value}; retained as dict until channel mapping
    measurements: dict[str, float] | None = None
    # location
    latitude: float | None = None
    longitude: float | None = None
    elevation: float | None = None
    # alert
    alert_type: str | None = None
    alert_level: AlertLevel = AlertLevel.INFO
    alert_message: str | None = None
    # command response (Acknowledge)
    originating_event_id: str | None = None
    response: str | None = None
    # state change
    attribute: str | None = None
    state_type: str | None = None
    previous_state: str | None = None
    new_state: str | None = None
    # dedup
    alternate_id: str | None = None
    # free-form (registration device type/area tokens, stream ids, ...)
    extras: dict[str, Any] = dataclasses.field(default_factory=dict)
    metadata: dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def event_type(self) -> EventType | None:
        return {
            RequestType.DEVICE_MEASUREMENT: EventType.MEASUREMENT,
            RequestType.DEVICE_LOCATION: EventType.LOCATION,
            RequestType.DEVICE_ALERT: EventType.ALERT,
            RequestType.ACKNOWLEDGE: EventType.COMMAND_RESPONSE,
            RequestType.DEVICE_STATE_CHANGE: EventType.STATE_CHANGE,
        }.get(self.type)


class EventDecodeException(Exception):
    """Raised by decoders on malformed payloads; the event source routes the
    payload to the failed-decode dead letter (EventSourcesManager.java:212-220
    analog)."""
