"""Zero-copy ingest staging arenas.

The legacy batch path moves every decoded event through three host
buffers before the chip sees it: the decoder allocates fresh SoA output
arrays, ``_ingest_decoded`` copies the accepted rows into the
``HostEventBuffer``, and ``emit()`` re-allocates the buffer for the next
batch. On a 1-core driver those copies and allocations are a large slice
of the ~30x gap between the fused device step and the host e2e rate
(ISSUE 2 / BENCH_r05).

A :class:`StagingArena` is ONE preallocated SoA buffer holding both the
decoder's scratch columns (``rtype``/``ts64``/``level``) and the final
``EventBatch`` columns. The native scanner writes straight into the
final columns (``swtpu_decode_arena_*`` entry points take the arena's
column slices, including a strided ``aux[:, 0]`` lane), the commit pass
runs a handful of vectorized in-place transforms (type map, timestamp
relativization, alert-level fold), and the dispatch hands the SAME
arrays to the jit step — zero row-level Python, zero staging copies,
zero per-batch allocation.

The :class:`ArenaPool` rotates a small fixed set of arenas through
in-flight dispatches: an arena is recycled only once the step output it
fed reports ready (``jax.block_until_ready``), which guarantees the
host->device transfer of its arrays has completed — mutating a numpy
buffer while a transfer is still reading it would corrupt the batch.
With ``dispatch_depth`` >= 2 and ``n_arenas`` > depth, decode of batch
N+1 overlaps transfer/execution of batch N. An exhausted pool blocks on
the OLDEST in-flight dispatch (backpressure, counted in
``waits``) rather than allocating.
"""

from __future__ import annotations

import collections
import time

import numpy as np

from sitewhere_tpu.core.events import EventBatch
from sitewhere_tpu.core.types import AUX_LANES, NULL_ID


class ArenaStallError(RuntimeError):
    """``ArenaPool.acquire`` gave up waiting on a wedged in-flight
    dispatch (``timeout_s`` exceeded). Raised LOUDLY instead of hanging
    the ingest thread under the engine lock forever; the engine
    translates it to a shed + counter (ISSUE 9)."""


class StagingArena:
    """One preallocated SoA staging buffer of ``rows`` event slots.

    ``rows`` is ``batch_capacity * scan_chunk``: with ``scan_chunk`` K > 1
    the arena is consumed as K scan lanes of ``rows // K`` by the arena
    scan step (``pipeline.make_arena_scan_step``) — the ``seq`` column is
    pre-tiled per lane. ``cursor`` is the fill position; rows past the
    cursor at dispatch are masked invalid (free padding)."""

    __slots__ = ("rows", "channels", "lanes", "cursor", "traces",
                 "valid", "etype", "token_id", "tenant_id", "ts_ms",
                 "received_ms", "values", "vmask", "aux", "seq",
                 "rtype", "ts64", "level")

    def __init__(self, rows: int, channels: int, lanes: int = 1):
        if rows % max(1, lanes):
            raise ValueError(f"arena rows {rows} not divisible by "
                             f"{lanes} scan lanes")
        self.rows = rows
        self.channels = channels
        self.lanes = max(1, lanes)
        self.cursor = 0
        self.traces: list = []   # flight records of batches staged here
        # final EventBatch columns (the decoder + commit write these)
        self.valid = np.zeros(rows, np.bool_)
        self.etype = np.zeros(rows, np.int32)
        self.token_id = np.full(rows, NULL_ID, np.int32)
        self.tenant_id = np.full(rows, NULL_ID, np.int32)
        self.ts_ms = np.zeros(rows, np.int32)
        self.received_ms = np.zeros(rows, np.int32)
        self.values = np.zeros((rows, channels), np.float32)
        # uint8 storage, viewed as bool for the EventBatch (same layout);
        # the native decoder ABI wants uint8
        self.vmask = np.zeros((rows, channels), np.uint8)
        self.aux = np.full((rows, AUX_LANES), NULL_ID, np.int32)
        self.seq = np.tile(np.arange(rows // self.lanes, dtype=np.int32),
                           self.lanes)
        # decoder scratch columns (host-only, never transferred)
        self.rtype = np.empty(rows, np.int32)
        self.ts64 = np.empty(rows, np.int64)
        self.level = np.empty(rows, np.int32)

    @property
    def room(self) -> int:
        return self.rows - self.cursor

    @property
    def nbytes(self) -> int:
        """Host bytes this arena pins (data columns AND decoder scratch —
        all preallocated for the arena's lifetime; the memory ledger's
        per-arena unit). Derived from the array-valued slots so a future
        column is counted the day it is added."""
        return sum(v.nbytes for name in self.__slots__
                   if isinstance((v := getattr(self, name)), np.ndarray))

    def view_batch(self) -> EventBatch:
        """The full-capacity numpy-backed EventBatch over the arena's
        arrays (no copies; rows past the cursor must already be masked
        invalid by the dispatcher)."""
        return EventBatch(
            valid=self.valid,
            etype=self.etype,
            token_id=self.token_id,
            tenant_id=self.tenant_id,
            ts_ms=self.ts_ms,
            received_ms=self.received_ms,
            values=self.values,
            vmask=self.vmask.view(np.bool_),
            aux=self.aux,
            seq=self.seq,
        )

    def reset(self) -> None:
        """Make the arena fillable again. Stale column contents are inert
        (every row is dead until the next commit sets its ``valid``); the
        valid mask itself is cleared so a stale True can never leak
        through a partial dispatch."""
        self.cursor = 0
        self.valid[:] = False
        self.traces = []


class ShardedStagingArena:
    """Stacked ``[n_shards, rows]`` staging arena for the SPMD engine.

    Each shard owns one contiguous lane of ``rows`` slots (C-order, so a
    lane is one flat memcpy-able slab) with its own fill ``cursors[s]``;
    the batch-decode path scatters routed rows into the lanes and
    ``view_batch()`` hands the SAME arrays to the shard_mapped fused step
    as a stacked EventBatch whose leading axis matches the mesh sharding.
    With ``lanes`` (= scan_chunk) K > 1 each shard's lane is consumed as
    K scan chunks of ``rows // K`` by the packed sharded scan step.

    No decoder scratch columns: the SPMD path runs the commit transforms
    on the decoder's flat SoA output BEFORE the scatter, so only final
    EventBatch columns live here."""

    __slots__ = ("n_shards", "rows", "channels", "lanes", "cursors",
                 "traces", "valid", "etype", "token_id", "tenant_id",
                 "ts_ms", "received_ms", "values", "vmask", "aux", "seq")

    def __init__(self, n_shards: int, rows: int, channels: int,
                 lanes: int = 1):
        if rows % max(1, lanes):
            raise ValueError(f"arena rows {rows} not divisible by "
                             f"{lanes} scan lanes")
        self.n_shards = n_shards
        self.rows = rows
        self.channels = channels
        self.lanes = max(1, lanes)
        self.cursors = np.zeros(n_shards, np.int64)
        self.traces: list = []
        s = n_shards
        self.valid = np.zeros((s, rows), np.bool_)
        self.etype = np.zeros((s, rows), np.int32)
        self.token_id = np.full((s, rows), NULL_ID, np.int32)
        self.tenant_id = np.full((s, rows), NULL_ID, np.int32)
        self.ts_ms = np.zeros((s, rows), np.int32)
        self.received_ms = np.zeros((s, rows), np.int32)
        self.values = np.zeros((s, rows, channels), np.float32)
        self.vmask = np.zeros((s, rows, channels), np.uint8)
        self.aux = np.full((s, rows, AUX_LANES), NULL_ID, np.int32)
        self.seq = np.tile(
            np.tile(np.arange(rows // self.lanes, dtype=np.int32),
                    self.lanes), (s, 1))

    @property
    def cursor(self) -> int:
        """Total staged rows across every shard lane (the single-arena
        ``cursor`` seam: flush/quiesce callers only test truthiness)."""
        return int(self.cursors.sum())

    @property
    def nbytes(self) -> int:
        return sum(v.nbytes for name in self.__slots__
                   if isinstance((v := getattr(self, name)), np.ndarray))

    def view_batch(self) -> EventBatch:
        """The stacked ``[n_shards, rows]`` EventBatch over the arena's
        arrays (no copies; lanes past each shard's cursor must already be
        masked invalid by the dispatcher)."""
        return EventBatch(
            valid=self.valid,
            etype=self.etype,
            token_id=self.token_id,
            tenant_id=self.tenant_id,
            ts_ms=self.ts_ms,
            received_ms=self.received_ms,
            values=self.values,
            vmask=self.vmask.view(np.bool_),
            aux=self.aux,
            seq=self.seq,
        )

    def reset(self) -> None:
        self.cursors[:] = 0
        self.valid[:] = False
        self.traces = []


class ArenaPool:
    """Fixed pool of staging arenas rotating through in-flight dispatches.

    Not thread-safe by itself — the engine serializes acquire/retire
    under its lock (the same discipline as every other staging mutation).
    ``factory`` swaps the arena type (the SPMD engine pools
    :class:`ShardedStagingArena`); the pool itself only needs ``reset()``
    and ``nbytes`` from its arenas."""

    def __init__(self, n_arenas: int, rows: int, channels: int,
                 lanes: int = 1, factory=None):
        if n_arenas < 1:
            raise ValueError("arena pool needs at least one arena")
        self.n_arenas = n_arenas
        make = factory or (lambda: StagingArena(rows, channels, lanes))
        self._free: list = [make() for _ in range(n_arenas)]
        # (arena, ticket): ticket is any array from the dispatch that fed
        # on the arena; ticket-ready implies the transfer out of the
        # arena's host buffers has completed
        self._inflight: collections.deque = collections.deque()
        self.waits = 0   # times acquire had to block on the oldest dispatch
        self._occupancy_hwm = 0   # max arenas simultaneously out of the
                                  # free list (capacity headroom, ISSUE 11)
        # per-arena footprint cached at construction: nbytes must hold
        # even at the instant every arena is checked out (fill arena +
        # in-flight dispatches can empty both lists)
        self._arena_nbytes = self._free[0].nbytes

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def inflight_count(self) -> int:
        return len(self._inflight)

    @property
    def nbytes(self) -> int:
        """Host bytes held by the pool's staging buffers (free, filling
        and in-flight arenas all stay allocated for the pool's lifetime
        — the memory-ledger component; sized from construction-time
        geometry, so it holds even when every arena is checked out)."""
        return self.n_arenas * self._arena_nbytes

    def take_occupancy_hwm(self, reset: bool = True) -> int:
        """Max arenas simultaneously out of the free pool since the last
        reset. The Prometheus scrape resets it (each sample = worst case
        this window); peeks pass ``reset=False``."""
        current = self.n_arenas - len(self._free)
        hwm = max(self._occupancy_hwm, current)
        if reset:
            self._occupancy_hwm = current
        return hwm

    def acquire(self, timeout_s: float | None = None):
        """A fillable arena; blocks on the oldest in-flight dispatch when
        every arena is tied up (ingest backpressure). With ``timeout_s``
        the block is BOUNDED: a dispatch that never completes (wedged
        device runtime, dead transfer stream) raises a typed
        :class:`ArenaStallError` instead of hanging the ingest thread
        silently — the caller sheds the batch and the failure is
        visible."""
        self._reclaim_ready()
        if not self._free:
            self.waits += 1
            self._reclaim_oldest(timeout_s)
        arena = self._free.pop()
        occupied = self.n_arenas - len(self._free)
        if occupied > self._occupancy_hwm:
            self._occupancy_hwm = occupied
        return arena

    def retire(self, arena, ticket, traces: list = ()) -> None:
        """Hand a dispatched arena back; it recycles once ``ticket`` is
        ready. ``traces`` are the flight records of the batches it
        carried — the recycle wait already observes the step output, so
        stamping their ``device_ready`` here costs no extra sync."""
        self._inflight.append((arena, ticket, tuple(traces)))

    @staticmethod
    def _mark_ready(traces) -> None:
        # overwrite, like every other stage mark: a batch spanning
        # several arenas keeps the LAST chunk's readiness, matching its
        # last-dispatch stamp (drain's backfill, by contrast, only fills
        # the stage when no reclaim ever observed it)
        for rec in traces:
            rec.mark("device_ready")

    def _reclaim_oldest(self, timeout_s: float | None = None) -> None:
        import jax

        if timeout_s is not None:
            # bounded wait: poll the ticket's readiness (jax has no timed
            # block) and refuse to pop an arena we may never get back. A
            # ticket without is_ready (plain numpy in tests) is treated
            # as ready — block_until_ready returns immediately for it.
            ticket = self._inflight[0][1]
            is_ready = getattr(ticket, "is_ready", None)
            deadline = time.monotonic() + timeout_s
            while is_ready is not None and not is_ready():
                if time.monotonic() >= deadline:
                    raise ArenaStallError(
                        f"arena recycle stalled: oldest of "
                        f"{len(self._inflight)} in-flight dispatch(es) "
                        f"not ready after {timeout_s:.3f}s")
                time.sleep(min(0.001, timeout_s / 10))
        arena, ticket, traces = self._inflight.popleft()
        jax.block_until_ready(ticket)
        self._mark_ready(traces)
        arena.reset()
        self._free.append(arena)

    def _reclaim_ready(self) -> None:
        """Opportunistically recycle arenas whose dispatches already
        finished (no blocking)."""
        while self._inflight:
            ticket = self._inflight[0][1]
            is_ready = getattr(ticket, "is_ready", None)
            if is_ready is None or not is_ready():
                return
            arena, _, traces = self._inflight.popleft()
            self._mark_ready(traces)
            arena.reset()
            self._free.append(arena)

    def drain(self) -> None:
        """Block until every in-flight arena is reclaimable (shutdown /
        test barrier)."""
        while self._inflight:
            self._reclaim_oldest()
