"""Event deduplicators (reference: sources/deduplicator/*).

``AlternateIdDeduplicator`` mirrors the reference's strategy of checking the
event's alternate id against already-persisted events
(AlternateIdDeduplicator.java uses getDeviceEventByAlternateId); here the
check is a host-side bounded LRU set per tenant — O(1), no store round trip,
sized to cover the at-least-once redelivery window.

``ScriptedDeduplicator`` takes a user Python predicate (Groovy analog).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Protocol

from sitewhere_tpu.ingest.requests import DecodedRequest


class Deduplicator(Protocol):
    def is_duplicate(self, request: DecodedRequest) -> bool:
        ...


class AlternateIdDeduplicator:
    """Bounded LRU of (tenant, token, alternate_id) triples."""

    def __init__(self, capacity: int = 1 << 16):
        self.capacity = capacity
        self._seen: OrderedDict[tuple, None] = OrderedDict()

    def is_duplicate(self, request: DecodedRequest) -> bool:
        if request.alternate_id is None:
            return False
        key = (request.tenant, request.device_token, request.alternate_id)
        if key in self._seen:
            self._seen.move_to_end(key)
            return True
        self._seen[key] = None
        if len(self._seen) > self.capacity:
            self._seen.popitem(last=False)
        return False


class ScriptedDeduplicator:
    """User-provided predicate (reference: ScriptedEventDeduplicator)."""

    def __init__(self, fn: Callable[[DecodedRequest], bool]):
        self.fn = fn

    def is_duplicate(self, request: DecodedRequest) -> bool:
        return bool(self.fn(request))
