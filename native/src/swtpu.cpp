// swtpu: native host data-plane for the TPU event engine.
//
// The reference's ingest edge burns JVM cycles per message (Jackson
// ObjectMapper per payload in sources/decoder/json/JsonDeviceRequestDecoder,
// per-message Kafka serialization). Here the host hot loop — JSON
// device-request decode + token interning + SoA batch packing — is native:
// a zero-allocation streaming JSON scanner fills the caller's numpy arrays
// directly, and device tokens / measurement names / alert types are interned
// in open-addressing string tables so the TPU batch carries int32 ids only.
//
// C ABI (ctypes-friendly); no external dependencies.

#include <charconv>
#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <cmath>
#include <string>
#include <vector>

extern "C" {

// ---------------------------------------------------------------- interner

struct Slot {
    int32_t id;       // index into strings, -1 empty
    int32_t len;      // string length
    uint64_t prefix;  // first <=8 bytes, zero-padded
};

struct Interner {
    // open addressing, power-of-two capacity. Each slot inlines the
    // string's length + 8-byte prefix: a probe for a short string
    // (tokens like "lg-1234") resolves WITHOUT dereferencing the heap
    // std::string — one cache line instead of two dependent misses —
    // and longer strings memcmp only after the prefix matches. The slot
    // table starts small and doubles at 50% load: interners sized for
    // millions of entries (event-id/alternate-id tables) cost a few KB
    // until strings actually arrive.
    std::vector<Slot> slots;
    std::vector<std::string> strings;
    uint64_t mask;
    int32_t max_entries;
};

static inline uint64_t prefix8(const char* s, int32_t n) {
    uint64_t p = 0;
    memcpy(&p, s, n < 8 ? n : 8);
    return p;
}

static uint64_t hash_bytes(const char* s, int n) {
    // FNV-1a folded over 8-byte lanes: ~4x fewer multiplies than the
    // byte-at-a-time form on typical 8-20 byte tokens/names. The hash is
    // ONLY an in-memory slot placement (ids are insertion-ordered and
    // snapshots persist strings, not slots) — free to change. Cluster
    // rank ownership uses its own byte-exact FNV in parallel/cluster.py.
    uint64_t h = 1469598103934665603ull;
    while (n >= 8) {
        uint64_t k;
        memcpy(&k, s, 8);
        h = (h ^ k) * 1099511628211ull;
        s += 8;
        n -= 8;
    }
    while (n-- > 0) {
        h ^= (unsigned char)*s++;
        h *= 1099511628211ull;
    }
    // Finalizer (murmur3 fmix64): multiplication only propagates bits
    // UPWARD, so without this a trailing lane's high bytes (the LAST
    // char of an 8/16-byte token like "device-7") never reach the
    // masked low bits and every such token lands on one slot.
    h ^= h >> 33;
    h *= 0xff51afd7ed558ccdull;
    h ^= h >> 33;
    return h;
}

Interner* swtpu_interner_create(int32_t max_entries) {
    uint64_t cap = 64;
    uint64_t full = 64;
    while (full < (uint64_t)max_entries * 2) full <<= 1;
    if (full < cap) full = cap;
    if (cap > full) cap = full;
    // lazy table: start at <=1024 slots, grow toward the full capacity
    while (cap < full && cap < 1024) cap <<= 1;
    auto* in = new Interner();
    in->slots.assign(cap, Slot{-1, 0, 0});
    in->mask = cap - 1;
    in->max_entries = max_entries;
    in->strings.reserve(64);
    return in;
}

void swtpu_interner_destroy(Interner* in) { delete in; }

// double the slot table and rehash (insertion order — the ids — is
// untouched; the hash is only an in-memory placement)
static void interner_grow(Interner* in) {
    std::vector<Slot> ns(in->slots.size() * 2, Slot{-1, 0, 0});
    uint64_t nm = ns.size() - 1;
    for (const Slot& sl : in->slots) {
        if (sl.id < 0) continue;
        const std::string& t = in->strings[sl.id];
        uint64_t h = hash_bytes(t.data(), (int)t.size()) & nm;
        while (ns[h].id >= 0) h = (h + 1) & nm;
        ns[h] = sl;
    }
    in->slots.swap(ns);
    in->mask = nm;
}

int32_t swtpu_intern(Interner* in, const char* s, int32_t n) {
    uint64_t h = hash_bytes(s, n) & in->mask;
    const uint64_t pfx = prefix8(s, n);
    while (true) {
        Slot& sl = in->slots[h];
        if (sl.id < 0) {
            if ((int32_t)in->strings.size() >= in->max_entries) return -1;
            if ((uint64_t)(in->strings.size() + 1) * 2
                > (uint64_t)in->slots.size()) {
                interner_grow(in);
                return swtpu_intern(in, s, n);   // re-probe the new table
            }
            int32_t id = (int32_t)in->strings.size();
            in->strings.emplace_back(s, n);
            sl = Slot{id, n, pfx};
            return id;
        }
        if (sl.len == n && sl.prefix == pfx &&
            (n <= 8 || memcmp(in->strings[sl.id].data(), s, n) == 0))
            return sl.id;
        h = (h + 1) & in->mask;
    }
}

int32_t swtpu_interner_lookup(Interner* in, const char* s, int32_t n) {
    uint64_t h = hash_bytes(s, n) & in->mask;
    const uint64_t pfx = prefix8(s, n);
    while (true) {
        const Slot& sl = in->slots[h];
        if (sl.id < 0) return -1;
        if (sl.len == n && sl.prefix == pfx &&
            (n <= 8 || memcmp(in->strings[sl.id].data(), s, n) == 0))
            return sl.id;
        h = (h + 1) & in->mask;
    }
}

int32_t swtpu_interner_size(Interner* in) { return (int32_t)in->strings.size(); }

// roll back to the first n entries (rejected-batch cleanup). Safe with
// linear probing because only the TAIL of insertion order is removed:
// every surviving entry was inserted before any removed one, so its probe
// chain never depended on a removed slot.
void swtpu_interner_truncate(Interner* in, int32_t n) {
    if (n < 0 || n >= (int32_t)in->strings.size()) return;
    for (auto& s : in->slots)
        if (s.id >= n) s = Slot{-1, 0, 0};
    in->strings.resize(n);
}

// copy string #id into out (cap bytes); returns length or -1
int32_t swtpu_interner_get(Interner* in, int32_t id, char* out, int32_t cap) {
    if (id < 0 || id >= (int32_t)in->strings.size()) return -1;
    const std::string& s = in->strings[id];
    int32_t n = (int32_t)s.size() < cap ? (int32_t)s.size() : cap;
    memcpy(out, s.data(), n);
    return (int32_t)s.size();
}

// ---------------------------------------------------------------- JSON scan

struct Scanner {
    const char* p;
    const char* end;
    bool ok;
};

static void skip_ws(Scanner& sc) {
    while (sc.p < sc.end && (*sc.p == ' ' || *sc.p == '\t' || *sc.p == '\n' || *sc.p == '\r'))
        sc.p++;
}

static bool expect(Scanner& sc, char c) {
    skip_ws(sc);
    if (sc.p < sc.end && *sc.p == c) { sc.p++; return true; }
    sc.ok = false;
    return false;
}

// parse a JSON string (assumes opening quote consumed is NOT done); writes
// unescaped content into buf, returns length or -1.
static int parse_string(Scanner& sc, char* buf, int cap) {
    skip_ws(sc);
    if (sc.p >= sc.end || *sc.p != '"') { sc.ok = false; return -1; }
    sc.p++;
    int n = 0;
    while (sc.p < sc.end) {
        char c = *sc.p++;
        if (c == '"') return n;
        if (c == '\\') {
            if (sc.p >= sc.end) break;
            char e = *sc.p++;
            switch (e) {
                case 'n': c = '\n'; break;
                case 't': c = '\t'; break;
                case 'r': c = '\r'; break;
                case 'b': c = '\b'; break;
                case 'f': c = '\f'; break;
                case 'u': {
                    if (sc.end - sc.p < 4) { sc.ok = false; return -1; }
                    int code = 0;
                    for (int i = 0; i < 4; i++) {
                        char h = *sc.p++;
                        code <<= 4;
                        if (h >= '0' && h <= '9') code |= h - '0';
                        else if (h >= 'a' && h <= 'f') code |= h - 'a' + 10;
                        else if (h >= 'A' && h <= 'F') code |= h - 'A' + 10;
                        else { sc.ok = false; return -1; }
                    }
                    // surrogate pair -> one non-BMP code point: the
                    // escaped and raw-UTF-8 forms of the same token must
                    // produce IDENTICAL bytes (intern identity + the
                    // route hash). Lone surrogates become '?'.
                    if (code >= 0xD800 && code < 0xDC00) {
                        int lo = -1;
                        if (sc.end - sc.p >= 6 && sc.p[0] == '\\'
                            && sc.p[1] == 'u') {
                            lo = 0;
                            for (int i = 2; i < 6 && lo >= 0; i++) {
                                char h = sc.p[i];
                                lo <<= 4;
                                if (h >= '0' && h <= '9') lo |= h - '0';
                                else if (h >= 'a' && h <= 'f') lo |= h - 'a' + 10;
                                else if (h >= 'A' && h <= 'F') lo |= h - 'A' + 10;
                                else lo = -1;
                            }
                        }
                        if (lo >= 0xDC00 && lo < 0xE000) {
                            sc.p += 6;
                            int cp = 0x10000 + ((code - 0xD800) << 10)
                                     + (lo - 0xDC00);
                            if (n + 4 <= cap) {
                                buf[n++] = (char)(0xF0 | (cp >> 18));
                                buf[n++] = (char)(0x80 | ((cp >> 12) & 0x3F));
                                buf[n++] = (char)(0x80 | ((cp >> 6) & 0x3F));
                                c = (char)(0x80 | (cp & 0x3F));
                            } else c = '?';
                        } else c = '?';
                        break;
                    }
                    if (code >= 0xDC00 && code < 0xE000) { c = '?'; break; }
                    // UTF-8 encode (BMP)
                    if (code < 0x80) { c = (char)code; }
                    else {
                        if (n + 3 < cap) {
                            if (code < 0x800) {
                                buf[n++] = (char)(0xC0 | (code >> 6));
                                c = (char)(0x80 | (code & 0x3F));
                            } else {
                                buf[n++] = (char)(0xE0 | (code >> 12));
                                buf[n++] = (char)(0x80 | ((code >> 6) & 0x3F));
                                c = (char)(0x80 | (code & 0x3F));
                            }
                        } else c = '?';
                    }
                    break;
                }
                default: c = e;
            }
        }
        if (n < cap) buf[n++] = c;
    }
    sc.ok = false;
    return -1;
}

// Parse a JSON string WITHOUT copying when it has no escapes (every key
// and nearly every value in the wire shapes): memchr (SIMD in libc)
// finds the closing quote, a second memchr proves no backslash precedes
// it, and *out points INTO the message buffer — valid for the whole
// batch call (packed buffer / pinned PyBytes). Escaped strings fall back
// to the unescaping copy into buf. Returns length or -1.
static int parse_string_view(Scanner& sc, const char** out, char* buf,
                             int cap) {
    skip_ws(sc);
    if (sc.p >= sc.end || *sc.p != '"') { sc.ok = false; return -1; }
    const char* s = sc.p + 1;
    const char* q =
        (const char*)memchr(s, '"', (size_t)(sc.end - s));
    if (q == nullptr) { sc.ok = false; return -1; }
    if (memchr(s, '\\', (size_t)(q - s)) == nullptr) {
        sc.p = q + 1;
        *out = s;
        int n = (int)(q - s);
        // clamp to the fallback's landing-pad capacity so a string's
        // interned identity never depends on which path parsed it. (For
        // >cap strings whose cap boundary splits a multibyte \u escape
        // the two JSON encodings can still truncate to different final
        // bytes — longstanding parse_string behavior; real tokens/names
        // are far under the 512/128-byte pads.)
        return n > cap ? cap : n;
    }
    int n = parse_string(sc, buf, cap);  // sc.p still at the open quote
    *out = buf;
    return n;
}

// Locale-independent, BOUNDED number parse (strtod was locale-aware,
// ~10x slower, and read past the message boundary — saved only by the
// buffer's trailing NUL). std::from_chars where the stdlib has the
// floating-point overload (gcc >= 11); otherwise a hand-rolled scan
// whose fast path (<= 15 mantissa digits, |exp10| <= 22 — every wire
// number this system emits) is exactly rounded via the classic Clinger
// power-of-ten argument, with a bounded-copy strtod fallback for the
// exotic rest.
#if defined(__cpp_lib_to_chars)
static double parse_number(Scanner& sc) {
    skip_ws(sc);
    double v = 0;
    auto res = std::from_chars(sc.p, sc.end, v);
    if (res.ec != std::errc() || res.ptr == sc.p) { sc.ok = false; return 0; }
    sc.p = res.ptr;
    return v;
}
#else
static double parse_number(Scanner& sc) {
    skip_ws(sc);
    const char* p = sc.p;
    const char* end = sc.end;
    const char* start = p;
    bool neg = false;
    if (p < end && *p == '-') { neg = true; p++; }
    uint64_t mant = 0;
    int ndig = 0;        // mantissa digits accumulated (cap 19 fits u64)
    int extra_exp = 0;   // integer digits past the cap shift the exponent
    bool any = false;
    while (p < end && *p >= '0' && *p <= '9') {
        any = true;
        if (ndig < 19) { mant = mant * 10 + (uint64_t)(*p - '0'); ndig++; }
        else extra_exp++;
        p++;
    }
    int frac = 0;
    if (p < end && *p == '.') {
        const char* fp = p + 1;
        while (fp < end && *fp >= '0' && *fp <= '9') {
            any = true;
            if (ndig < 19) {
                mant = mant * 10 + (uint64_t)(*fp - '0');
                ndig++;
                frac++;
            }
            fp++;
        }
        if (fp > p + 1) p = fp;   // lone '.' is not part of the number
    }
    if (!any) { sc.ok = false; return 0; }
    int esign = 1, eval = 0;
    if (p < end && (*p == 'e' || *p == 'E')) {
        const char* ep = p + 1;
        if (ep < end && (*ep == '+' || *ep == '-')) {
            if (*ep == '-') esign = -1;
            ep++;
        }
        bool edig = false;
        while (ep < end && *ep >= '0' && *ep <= '9') {
            if (eval < 10000) eval = eval * 10 + (*ep - '0');
            edig = true;
            ep++;
        }
        if (edig) p = ep;   // digit-less exponent: 'e' is not consumed
    }
    int exp10 = esign * eval - frac + extra_exp;
    double v;
    if (ndig <= 15 && exp10 >= -22 && exp10 <= 22) {
        static const double P10[23] = {
            1e0,  1e1,  1e2,  1e3,  1e4,  1e5,  1e6,  1e7,
            1e8,  1e9,  1e10, 1e11, 1e12, 1e13, 1e14, 1e15,
            1e16, 1e17, 1e18, 1e19, 1e20, 1e21, 1e22};
        // mant is exact (< 2^53), P10[k] is exact: ONE rounding step
        v = exp10 >= 0 ? (double)mant * P10[exp10]
                       : (double)mant / P10[-exp10];
    } else {
        // bounded copy: strtod never sees past the number. 512 matches
        // the string landing pads; a >511-char number token still
        // truncates (no real wire shape comes close)
        char nbuf[512];
        size_t ln = (size_t)(p - start);
        if (ln >= sizeof nbuf) ln = sizeof nbuf - 1;
        memcpy(nbuf, start, ln);
        nbuf[ln] = 0;
        v = strtod(nbuf, nullptr);
        sc.p = p;
        return v;        // sign already in the copied text
    }
    sc.p = p;
    return neg ? -v : v;
}
#endif

// skip any JSON value
static void skip_value(Scanner& sc);

static void skip_container(Scanner& sc, char open, char close) {
    int depth = 1;
    sc.p++;  // consume open
    while (sc.p < sc.end && depth > 0) {
        char c = *sc.p;
        if (c == '"') {
            char tmp[1];
            // fast string skip
            sc.p++;
            while (sc.p < sc.end && *sc.p != '"') {
                if (*sc.p == '\\') sc.p++;
                sc.p++;
            }
            if (sc.p < sc.end) sc.p++;
            continue;
        }
        if (c == open) depth++;
        else if (c == close) depth--;
        sc.p++;
    }
    (void)sizeof(char[1]);
}

static void skip_value(Scanner& sc) {
    skip_ws(sc);
    if (sc.p >= sc.end) { sc.ok = false; return; }
    char c = *sc.p;
    if (c == '{') { skip_container(sc, '{', '}'); return; }
    if (c == '[') { skip_container(sc, '[', ']'); return; }
    if (c == '"') { char tmp[8]; parse_string(sc, tmp, 0); return; }
    if (c == 't') { sc.p += 4; return; }
    if (c == 'f') { sc.p += 5; return; }
    if (c == 'n') { sc.p += 4; return; }
    parse_number(sc);
}

// number field that may legally hold a JSON literal (null/true/false):
// the literal is skipped and NaN returned so callers treat it as absent
// rather than failing the whole payload.
static double parse_number_or_literal(Scanner& sc) {
    skip_ws(sc);
    if (sc.p < sc.end && (*sc.p == 'n' || *sc.p == 't' || *sc.p == 'f')) {
        skip_value(sc);
        return std::nan("");
    }
    return parse_number(sc);
}

// ---------------------------------------------------------------- decoder

// request envelope types (must match ingest/requests.py RequestType mapping)
enum ReqType {
    RT_UNKNOWN = -1,
    RT_REGISTER = 0,
    RT_MEASUREMENT = 1,
    RT_LOCATION = 2,
    RT_ALERT = 3,
    RT_STATE_CHANGE = 4,
    RT_ACK = 5,
    RT_MAP = 6,   // MapDevice: routed to the host slow path like REGISTER
};

static int type_code(const char* s, int n) {
    if (n == 17 && !memcmp(s, "DeviceMeasurement", 17)) return RT_MEASUREMENT;
    if (n == 18 && !memcmp(s, "DeviceMeasurements", 18)) return RT_MEASUREMENT;
    if (n == 9 && !memcmp(s, "MapDevice", 9)) return RT_MAP;
    if (n == 14 && !memcmp(s, "DeviceLocation", 14)) return RT_LOCATION;
    if (n == 11 && !memcmp(s, "DeviceAlert", 11)) return RT_ALERT;
    if (n == 14 && !memcmp(s, "RegisterDevice", 14)) return RT_REGISTER;
    if (n == 17 && !memcmp(s, "DeviceStateChange", 17)) return RT_STATE_CHANGE;
    if (n == 11 && !memcmp(s, "Acknowledge", 11)) return RT_ACK;
    return RT_UNKNOWN;
}

static int alert_level_code(const char* s, int n) {
    if (n == 4 && !memcmp(s, "Info", 4)) return 0;
    if (n == 7 && !memcmp(s, "Warning", 7)) return 1;
    if (n == 5 && !memcmp(s, "Error", 5)) return 2;
    if (n == 8 && !memcmp(s, "Critical", 8)) return 3;
    return 0;
}

struct Decoder {
    Interner* tokens;       // device tokens (shared with engine)
    Interner* names;        // measurement names
    Interner* alert_types;  // alert types
    Interner* event_ids;    // alternate/correlation ids (aux1 lane)
};

Decoder* swtpu_decoder_create(Interner* tokens, int32_t name_cap,
                              int32_t alert_cap, int32_t event_cap) {
    auto* d = new Decoder();
    d->tokens = tokens;
    d->names = swtpu_interner_create(name_cap);
    d->alert_types = swtpu_interner_create(alert_cap);
    d->event_ids = swtpu_interner_create(event_cap);
    return d;
}

Interner* swtpu_decoder_names(Decoder* d) { return d->names; }
Interner* swtpu_decoder_alert_types(Decoder* d) { return d->alert_types; }
Interner* swtpu_decoder_event_ids(Decoder* d) { return d->event_ids; }

void swtpu_decoder_destroy(Decoder* d) {
    swtpu_interner_destroy(d->names);
    swtpu_interner_destroy(d->alert_types);
    swtpu_interner_destroy(d->event_ids);
    delete d;
}

// Decode n_msgs JSON device-request envelopes (concatenated in buf, message i
// at [offsets[i], offsets[i+1])) into SoA output arrays of length n_msgs:
//   out_rtype     int32: ReqType or -1 on decode failure
//   out_token     int32: interned device-token id (-1 when missing)
//   out_ts        int64: eventDate ms or -1
//   out_values    float32[n_msgs * channels]
//   out_chmask    uint8[n_msgs * channels]
//   out_aux0      int32: alert-type id / state attr id (-1 none)
//   out_level     int32: alert level
// Measurement names map to channel = name_id % channels; collisions counted
// in *out_collisions. Returns number successfully decoded.
}  // extern "C" (templates cannot carry C linkage; the batch decode
   // loops are templated over a message accessor so the packed-buffer
   // entry points and the Python-list entry points — swtpu_py.cpp —
   // share ONE loop body with zero indirection cost)

// ---------------------------------------------------------------- sinks
// The decode loops are additionally templated over an interning SINK so
// the single-threaded path (DirectSink: intern straight into the shared
// tables, today's behavior) and the sharded path (ShardSink below:
// read-only lookups against the shared tables + per-shard overlay for
// first-seen strings, merged deterministically afterwards) share the
// exact same scanner.

struct DirectSink {
    Decoder* d;
    int32_t token(int32_t row, const char* s, int32_t n) {
        (void)row;
        return swtpu_intern(d->tokens, s, n);
    }
    void meas(int32_t row, const char* s, int32_t n, double v,
              float* vrow, uint8_t* mrow, int32_t channels,
              int32_t* collisions) {
        (void)row;
        int32_t nid = swtpu_intern(d->names, s, n);
        if (nid >= 0) {
            if (nid >= channels) (*collisions)++;
            int ch = nid % channels;
            vrow[ch] = (float)v;
            mrow[ch] = 1;
        }
    }
    int32_t alert_type(int32_t row, const char* s, int32_t n) {
        (void)row;
        return swtpu_intern(d->alert_types, s, n);
    }
    int32_t alternate(int32_t row, const char* s, int32_t n) {
        (void)row;
        return swtpu_intern(d->event_ids, s, n);
    }
};

// Sharded decode: one wire batch splits into contiguous payload ranges,
// each decoded by one worker into a disjoint row range of the same
// arena. The shared interners are READ-ONLY during the scan (the engine
// lock serializes all mutation); strings not yet interned go into a
// per-shard OVERLAY table and their uses are recorded as patches. The
// serial merge then interns overlay tails in shard order — which IS
// first-occurrence row order, because shards are ordered row ranges and
// each overlay assigns local ids in first-occurrence order — so the
// final id assignment is byte-identical to a single-threaded scan.
// Provisional ids are encoded as (-2 - overlay_idx): distinguishable
// from both real ids (>= 0) and "absent" (-1), and patch application
// only overwrites cells still holding the matching provisional value
// (a later occurrence of the same key may have replaced it).

struct Patch {
    int32_t row;   // shard-relative row
    int32_t idx;   // overlay id
    float val;     // measurement value (SK_NAME only)
};

enum { SK_TOKEN = 0, SK_NAME = 1, SK_ALERT = 2, SK_ALTID = 3 };

struct ShardCtx {
    Decoder* d;
    Interner* ov[4];
    std::vector<Patch> patch[4];
    // row currently in "deferred" mode: once a row records ONE overlay
    // (first-seen) measurement name, its remaining name writes defer
    // too — patch replay then preserves the row's key order even when
    // a new and a known name alias the same lane (direct ids ride the
    // patch list bit-inverted: idx < 0 means final id ~idx)
    int32_t deferred_row;
};

struct ShardSink {
    ShardCtx* c;
    int32_t shared_or_patch(int kind, Interner* base, int32_t row,
                            const char* s, int32_t n) {
        int32_t id = swtpu_interner_lookup(base, s, n);
        if (id >= 0) return id;
        int32_t idx = swtpu_intern(c->ov[kind], s, n);
        if (idx < 0) return -1;   // overlay full: same as interner-full
        c->patch[kind].push_back(Patch{row, idx, 0.f});
        return -2 - idx;
    }
    int32_t token(int32_t row, const char* s, int32_t n) {
        return shared_or_patch(SK_TOKEN, c->d->tokens, row, s, n);
    }
    void meas(int32_t row, const char* s, int32_t n, double v,
              float* vrow, uint8_t* mrow, int32_t channels,
              int32_t* collisions) {
        int32_t nid = swtpu_interner_lookup(c->d->names, s, n);
        if (nid >= 0) {
            if (nid >= channels) (*collisions)++;
            if (c->deferred_row == row) {
                // this row already deferred a first-seen name: keep its
                // remaining writes in key order on the patch list too
                // (direct final id rides bit-inverted), so replay
                // matches the single-threaded last-write-wins per lane
                c->patch[SK_NAME].push_back(Patch{row, ~nid, (float)v});
                return;
            }
            int ch = nid % channels;
            vrow[ch] = (float)v;
            mrow[ch] = 1;
            return;
        }
        // first-seen name: its lane is unknown until the merge assigns
        // the final id — defer the lane write entirely (collision
        // accounting happens at patch time, against the final id)
        int32_t idx = swtpu_intern(c->ov[SK_NAME], s, n);
        if (idx < 0) return;
        c->deferred_row = row;
        c->patch[SK_NAME].push_back(Patch{row, idx, (float)v});
    }
    int32_t alert_type(int32_t row, const char* s, int32_t n) {
        return shared_or_patch(SK_ALERT, c->d->alert_types, row, s, n);
    }
    int32_t alternate(int32_t row, const char* s, int32_t n) {
        return shared_or_patch(SK_ALTID, c->d->event_ids, row, s, n);
    }
};

// ``aux0_stride``/``aux1_stride`` let the caller aim out_aux0/out_aux1
// at strided columns of a wider staging arena (row i lands at
// out_aux[i * stride]); the plain batch entry points pass 1.
template <class Sink, class GetMsg>
static int32_t decode_json_impl(
    int32_t n_msgs, int32_t channels,
    int32_t* out_rtype, int32_t* out_token, int64_t* out_ts,
    float* out_values, uint8_t* out_chmask,
    int32_t* out_aux0, int64_t aux0_stride,
    int32_t* out_aux1, int64_t aux1_stride,
    int32_t* out_level, int32_t* out_collisions,
    Sink& sink, GetMsg get_msg) {
    int32_t ok_count = 0;
    int32_t collisions = 0;
    char sbuf[512];

    for (int32_t i = 0; i < n_msgs; i++) {
        out_rtype[i] = -1;
        out_token[i] = -1;
        out_ts[i] = -1;
        out_aux0[(size_t)i * aux0_stride] = -1;
        out_aux1[(size_t)i * aux1_stride] = -1;
        out_level[i] = 0;
        memset(out_values + (size_t)i * channels, 0, sizeof(float) * channels);
        memset(out_chmask + (size_t)i * channels, 0, channels);

        auto mm = get_msg(i);
        Scanner sc{mm.first, mm.second, true};
        if (!expect(sc, '{')) continue;
        int rtype = RT_UNKNOWN;
        // deviceToken takes precedence over hardwareId (route_json_impl
        // and the Python partitioner agree); within one key the last
        // occurrence wins (json.loads dict semantics). -1 = missing;
        // sharded decode may hand back provisional ids <= -2.
        int32_t token_dt = -1;
        int32_t token_hw = -1;
        bool in_request_done = false;
        bool first = true;
        bool failed = false;

        while (sc.ok && !failed) {
            skip_ws(sc);
            if (sc.p < sc.end && *sc.p == '}') { sc.p++; break; }
            if (!first && !expect(sc, ',')) break;
            first = false;
            const char* kp;
            int klen = parse_string_view(sc, &kp, sbuf, sizeof(sbuf));
            if (klen < 0 || !expect(sc, ':')) { failed = true; break; }

            bool k_dt = (klen == 11 && !memcmp(kp, "deviceToken", 11));
            if (k_dt || (klen == 10 && !memcmp(kp, "hardwareId", 10))) {
                const char* vp;
                int n = parse_string_view(sc, &vp, sbuf, sizeof(sbuf));
                if (n < 0) { failed = true; break; }
                int32_t tid = sink.token(i, vp, n);
                if (k_dt) token_dt = tid;
                else token_hw = tid;
            } else if (klen == 4 && !memcmp(kp, "type", 4)) {
                const char* vp;
                int n = parse_string_view(sc, &vp, sbuf, sizeof(sbuf));
                if (n < 0) { failed = true; break; }
                rtype = type_code(vp, n);
            } else if (klen == 7 && !memcmp(kp, "request", 7)) {
                // parse the request object with the already-known or
                // not-yet-known type: collect generically
                skip_ws(sc);
                if (sc.p >= sc.end || *sc.p != '{') { skip_value(sc); continue; }
                sc.p++;
                bool rfirst = true;
                float lat = 0, lon = 0, elev = 0;
                bool have_loc = false;
                char mname[128];  // slow-path landing pad for "name":
                const char* mname_p = nullptr;  // sbuf is reused per key,
                int mname_len = -1;             // mname must survive the loop
                double mval = 0; bool have_mval = false;
                while (sc.ok) {
                    skip_ws(sc);
                    if (sc.p < sc.end && *sc.p == '}') { sc.p++; break; }
                    if (!rfirst && !expect(sc, ',')) break;
                    rfirst = false;
                    const char* rkp;
                    int rk = parse_string_view(sc, &rkp, sbuf, sizeof(sbuf));
                    if (rk < 0 || !expect(sc, ':')) { failed = true; break; }
                    // dispatch on (length<<8 | first char): one jump + at
                    // most one confirming memcmp per key instead of a
                    // compare chain (VERDICT r3 scanner hot-loop
                    // follow-up). Unknown keys fall through to
                    // skip_value via the shared default.
                    bool handled = true;
                    switch (rk > 0 ? ((rk << 8) | (unsigned char)rkp[0])
                                   : 0) {
                    case (9 << 8) | 'e':   // eventDate | elevation
                        if (rkp[1] == 'v' && !memcmp(rkp, "eventDate", 9)) {
                            skip_ws(sc);
                            if (sc.p < sc.end && *sc.p == '"') skip_value(sc);  // ISO dates -> host path
                            else {
                                double tv = parse_number_or_literal(sc);
                                if (!std::isnan(tv)) out_ts[i] = (int64_t)tv;
                            }
                        } else if (rkp[1] == 'l' && !memcmp(rkp, "elevation", 9)) {
                            double dv = parse_number_or_literal(sc);
                            if (!std::isnan(dv)) elev = (float)dv;
                        } else handled = false;
                        break;
                    case (12 << 8) | 'm':  // measurements
                        if (memcmp(rkp, "measurements", 12)) { handled = false; break; }
                        skip_ws(sc);
                        if (sc.p < sc.end && *sc.p == '{') {
                            sc.p++;
                            bool mfirst = true;
                            while (sc.ok) {
                                skip_ws(sc);
                                if (sc.p < sc.end && *sc.p == '}') { sc.p++; break; }
                                if (!mfirst && !expect(sc, ',')) break;
                                mfirst = false;
                                const char* np;
                                int nn = parse_string_view(sc, &np, sbuf,
                                                           sizeof(sbuf));
                                if (nn < 0 || !expect(sc, ':')) { failed = true; break; }
                                double v = parse_number_or_literal(sc);
                                if (std::isnan(v)) continue;
                                sink.meas(i, np, nn, v,
                                          out_values + (size_t)i * channels,
                                          out_chmask + (size_t)i * channels,
                                          channels, &collisions);
                            }
                        } else skip_value(sc);
                        break;
                    case (4 << 8) | 'n':   // name
                        if (memcmp(rkp, "name", 4)) { handled = false; break; }
                        mname_len = parse_string_view(sc, &mname_p, mname,
                                                      sizeof(mname));
                        if (mname_len < 0) { failed = true; }
                        break;
                    case (5 << 8) | 'v':   // value
                        if (memcmp(rkp, "value", 5)) { handled = false; break; }
                        mval = parse_number_or_literal(sc);
                        have_mval = !std::isnan(mval);
                        break;
                    case (8 << 8) | 'l': { // latitude
                        if (memcmp(rkp, "latitude", 8)) { handled = false; break; }
                        double dv = parse_number_or_literal(sc);
                        if (!std::isnan(dv)) { lat = (float)dv; have_loc = true; }
                        break;
                    }
                    case (9 << 8) | 'l': { // longitude
                        if (memcmp(rkp, "longitude", 9)) { handled = false; break; }
                        double dv = parse_number_or_literal(sc);
                        if (!std::isnan(dv)) { lon = (float)dv; have_loc = true; }
                        break;
                    }
                    case (5 << 8) | 'l':   // level
                        if (memcmp(rkp, "level", 5)) { handled = false; break; }
                        skip_ws(sc);
                        if (sc.p < sc.end && *sc.p == '"') {
                            const char* vp;
                            int n = parse_string_view(sc, &vp, sbuf,
                                                      sizeof(sbuf));
                            if (n >= 0) out_level[i] = alert_level_code(vp, n);
                        } else {
                            double dv = parse_number_or_literal(sc);
                            if (!std::isnan(dv)) out_level[i] = (int32_t)dv;
                        }
                        break;
                    case (4 << 8) | 't': { // type
                        if (memcmp(rkp, "type", 4)) { handled = false; break; }
                        const char* vp;
                        int n = parse_string_view(sc, &vp, sbuf, sizeof(sbuf));
                        if (n >= 0)
                            out_aux0[(size_t)i * aux0_stride] =
                                sink.alert_type(i, vp, n);
                        break;
                    }
                    case (11 << 8) | 'a': { // alternateId -> aux1 lane
                        if (memcmp(rkp, "alternateId", 11)) { handled = false; break; }
                        skip_ws(sc);
                        if (sc.p >= sc.end || *sc.p != '"') {
                            skip_value(sc);   // non-string id: absent
                            break;
                        }
                        const char* vp;
                        int n = parse_string_view(sc, &vp, sbuf, sizeof(sbuf));
                        if (n >= 0)
                            out_aux1[(size_t)i * aux1_stride] =
                                sink.alternate(i, vp, n);
                        break;
                    }
                    default:
                        handled = false;
                    }
                    if (failed) break;
                    if (!handled) skip_value(sc);
                }
                if (mname_len >= 0 && have_mval) {
                    sink.meas(i, mname_p, mname_len, mval,
                              out_values + (size_t)i * channels,
                              out_chmask + (size_t)i * channels,
                              channels, &collisions);
                }
                if (have_loc) {
                    out_values[(size_t)i * channels + 0] = lat;
                    out_values[(size_t)i * channels + 1] = lon;
                    out_values[(size_t)i * channels + 2] = elev;
                    out_chmask[(size_t)i * channels + 0] = 1;
                    out_chmask[(size_t)i * channels + 1] = 1;
                    out_chmask[(size_t)i * channels + 2] = 1;
                }
                in_request_done = true;
            } else {
                skip_value(sc);
            }
        }

        // -1 = missing; real ids (>= 0) AND provisional shard ids
        // (<= -2) both count as present
        int32_t token = token_dt != -1 ? token_dt : token_hw;
        if (!failed && sc.ok && rtype != RT_UNKNOWN && token != -1) {
            out_rtype[i] = rtype;
            out_token[i] = token;
            ok_count++;
        }
        (void)in_request_done;
    }
    *out_collisions = collisions;
    return ok_count;
}

// Batched decode of the compact flat BINARY wire format (the "protobuf"
// ingest slot; framing defined by ingest/decoders.py encode_binary_request):
//   u8 version(=1)  u8 type  u16le token_len  token  i64le event_ts(-1=now)
//   type 1 measurement: u16le n { u16le name_len name f64le value }*
//   type 2 location:    f64le lat lon elev (NaN = absent coordinate)
//   type 3 alert:       u16le tlen type  u8 level  u16le mlen message
//   type 4 register / 5 ack: header only
// Outputs use the same contract as swtpu_decode_batch.
template <class Sink, class GetMsg>
static int32_t decode_binary_impl(
    int32_t n_msgs, int32_t channels,
    int32_t* out_rtype, int32_t* out_token, int64_t* out_ts,
    float* out_values, uint8_t* out_chmask,
    int32_t* out_aux0, int64_t aux0_stride,
    int32_t* out_aux1, int64_t aux1_stride,
    int32_t* out_level, int32_t* out_collisions,
    Sink& sink, GetMsg get_msg) {
    // wire type id -> ReqType (ingest/decoders.py _BIN_TYPES)
    static const int32_t WIRE2RT[6] = {RT_UNKNOWN, RT_MEASUREMENT,
                                       RT_LOCATION, RT_ALERT, RT_REGISTER,
                                       RT_ACK};
    int32_t ok_count = 0;
    int32_t collisions = 0;
    for (int32_t i = 0; i < n_msgs; i++) {
        out_rtype[i] = -1;
        out_token[i] = -1;
        out_ts[i] = -1;
        out_aux0[(size_t)i * aux0_stride] = -1;
        // the binary wire format carries no alternate id (see
        // ingest/decoders.py encode_binary_request): aux1 stays absent
        out_aux1[(size_t)i * aux1_stride] = -1;
        out_level[i] = 0;
        memset(out_values + (size_t)i * channels, 0,
               sizeof(float) * channels);
        memset(out_chmask + (size_t)i * channels, 0, channels);

        auto mm = get_msg(i);
        const uint8_t* p = (const uint8_t*)mm.first;
        const uint8_t* end = (const uint8_t*)mm.second;
        auto need = [&](size_t n) { return (size_t)(end - p) >= n; };
        auto u16 = [&]() { uint16_t v = (uint16_t)(p[0] | (p[1] << 8)); p += 2; return v; };

        if (!need(4)) continue;
        uint8_t ver = *p++;
        uint8_t wire_type = *p++;
        if (ver != 1 || wire_type == 0 || wire_type > 5) continue;
        uint16_t tlen = u16();
        if (!need((size_t)tlen + 8)) continue;
        int32_t token = sink.token(i, (const char*)p, tlen);
        p += tlen;
        int64_t ts;
        memcpy(&ts, p, 8);
        p += 8;
        int32_t rtype = WIRE2RT[wire_type];
        bool failed = false;

        if (rtype == RT_MEASUREMENT) {
            if (!need(2)) continue;
            uint16_t n = u16();
            for (uint16_t k = 0; k < n && !failed; k++) {
                if (!need(2)) { failed = true; break; }
                uint16_t nlen = u16();
                if (!need((size_t)nlen + 8)) { failed = true; break; }
                const char* np = (const char*)p;
                p += nlen;
                double v;
                memcpy(&v, p, 8);
                p += 8;
                sink.meas(i, np, nlen, v,
                          out_values + (size_t)i * channels,
                          out_chmask + (size_t)i * channels,
                          channels, &collisions);
            }
        } else if (rtype == RT_LOCATION) {
            if (!need(24)) continue;
            double lat, lon, elev;
            memcpy(&lat, p, 8);
            memcpy(&lon, p + 8, 8);
            memcpy(&elev, p + 16, 8);
            p += 24;
            if (!std::isnan(lat) && !std::isnan(lon)) {
                out_values[(size_t)i * channels + 0] = (float)lat;
                out_values[(size_t)i * channels + 1] = (float)lon;
                out_values[(size_t)i * channels + 2] =
                    std::isnan(elev) ? 0.0f : (float)elev;
                out_chmask[(size_t)i * channels + 0] = 1;
                out_chmask[(size_t)i * channels + 1] = 1;
                out_chmask[(size_t)i * channels + 2] = 1;
            }
        } else if (rtype == RT_ALERT) {
            if (!need(2)) continue;
            uint16_t tl = u16();
            if (!need((size_t)tl + 1)) continue;
            out_aux0[(size_t)i * aux0_stride] =
                sink.alert_type(i, (const char*)p, tl);
            p += tl;
            out_level[i] = *p++;
        }
        if (failed || token == -1) continue;  // interner-full = decode failure
        out_ts[i] = ts;
        out_rtype[i] = rtype;
        out_token[i] = token;
        ok_count++;
    }
    *out_collisions = collisions;
    return ok_count;
}

// ------------------------------------------------------------ cluster route
// Owning-rank partition WITHOUT a decode: the cluster facade needs only
// the device token's FNV-1a hash to pick the owner rank (the Kafka
// producer partitioner analog, parallel/cluster.py:owner_rank — byte-
// exact same hash). The JSON scan stops at the top level and skips every
// value except deviceToken/hardwareId, so routing costs a fraction of a
// full decode; the Python fallback paid a complete json.loads per
// payload here.

static uint64_t fnv1a_route(const char* s, int n) {
    uint64_t h = 0xcbf29ce484222325ull;
    for (int i = 0; i < n; i++) {
        h ^= (unsigned char)s[i];
        h *= 0x100000001b3ull;
    }
    return h;
}

static bool utf8_valid(const unsigned char* s, int n) {
    // strict (matches Python bytes.decode): rejects overlongs (E0 needs
    // 2nd byte >= A0, F0 needs >= 90), encoded surrogates (ED needs 2nd
    // byte <= 9F), and beyond-U+10FFFF (F4 needs 2nd byte <= 8F)
    int i = 0;
    while (i < n) {
        unsigned char c = s[i];
        int follow;
        if (c < 0x80) { i++; continue; }
        else if ((c & 0xE0) == 0xC0 && c >= 0xC2) follow = 1;
        else if ((c & 0xF0) == 0xE0) follow = 2;
        else if ((c & 0xF8) == 0xF0 && c <= 0xF4) follow = 3;
        else return false;
        if (i + follow >= n) return false;
        for (int k = 1; k <= follow; k++)
            if ((s[i + k] & 0xC0) != 0x80) return false;
        unsigned char c1 = s[i + 1];
        if ((c == 0xE0 && c1 < 0xA0) || (c == 0xED && c1 > 0x9F) ||
            (c == 0xF0 && c1 < 0x90) || (c == 0xF4 && c1 > 0x8F))
            return false;
        i += follow + 1;
    }
    return true;
}

// out_rank[i] = owner rank, or -1 when unroutable (no usable token /
// parse failure) — the caller keeps those local, where the engine's
// dead-letter path owns them. Mirrors the Python fallback exactly:
// deviceToken takes precedence over hardwareId, last occurrence of a
// repeated key wins (json.loads dict semantics), empty/non-string
// values fall through.
template <class GetMsg>
static void route_json_impl(int32_t n_msgs, int32_t n_ranks,
                            int32_t* out_rank, GetMsg get_msg) {
    char kbuf[512];
    // value cap MUST equal the decoder's sbuf cap: the interner sees at
    // most 512 token bytes, so hashing more would route two tokens that
    // intern identically (same 512-byte prefix) to different ranks
    char vbuf[512];
    for (int32_t i = 0; i < n_msgs; i++) {
        out_rank[i] = -1;
        auto mm = get_msg(i);
        Scanner sc{mm.first, mm.second, true};
        if (!expect(sc, '{')) continue;
        bool first = true;
        bool have_dt = false, have_hw = false;
        uint64_t h_dt = 0, h_hw = 0;
        while (sc.ok) {
            skip_ws(sc);
            if (sc.p < sc.end && *sc.p == '}') { sc.p++; break; }
            if (!first && !expect(sc, ',')) break;
            first = false;
            const char* kp;
            int klen = parse_string_view(sc, &kp, kbuf, sizeof(kbuf));
            if (klen < 0 || !expect(sc, ':')) break;
            bool is_dt = (klen == 11 && !memcmp(kp, "deviceToken", 11));
            bool is_hw = (klen == 10 && !memcmp(kp, "hardwareId", 10));
            if (is_dt || is_hw) {
                skip_ws(sc);
                if (sc.p < sc.end && *sc.p == '"') {
                    const char* vp;
                    int n = parse_string_view(sc, &vp, vbuf, sizeof(vbuf));
                    if (n < 0) break;
                    if (is_dt) { have_dt = n > 0; h_dt = fnv1a_route(vp, n); }
                    else       { have_hw = n > 0; h_hw = fnv1a_route(vp, n); }
                } else {
                    skip_value(sc);   // non-string token: key is absent
                    if (is_dt) have_dt = false;
                    else have_hw = false;
                }
            } else {
                skip_value(sc);
            }
        }
        if (have_dt) out_rank[i] = (int32_t)(h_dt % (uint64_t)n_ranks);
        else if (have_hw) out_rank[i] = (int32_t)(h_hw % (uint64_t)n_ranks);
    }
}

// Binary wire: token at [4, 4+tlen) after u8 ver, u8 type, u16le tlen
// (ingest/decoders.py:binary_token_of — including its UTF-8 validity
// gate, so native and fallback route identically).
template <class GetMsg>
static void route_binary_impl(int32_t n_msgs, int32_t n_ranks,
                              int32_t* out_rank, GetMsg get_msg) {
    for (int32_t i = 0; i < n_msgs; i++) {
        out_rank[i] = -1;
        auto mm = get_msg(i);
        const unsigned char* p = (const unsigned char*)mm.first;
        int64_t len = mm.second - mm.first;
        if (len < 4 || p[0] != 1) continue;
        uint16_t tlen = (uint16_t)(p[2] | (p[3] << 8));
        if (len < 4 + (int64_t)tlen) continue;
        if (!utf8_valid(p + 4, tlen)) continue;
        out_rank[i] = (int32_t)(fnv1a_route((const char*)p + 4, tlen)
                                % (uint64_t)n_ranks);
    }
}

// packed-buffer entry points (the ctypes ABI): message i lives at
// [offsets[i], offsets[i+1]) of one contiguous buffer
struct PackedMsgs {
    const char* buf;
    const int64_t* offsets;
    std::pair<const char*, const char*> operator()(int32_t i) const {
        return {buf + offsets[i], buf + offsets[i + 1]};
    }
};

extern "C" {

int32_t swtpu_decode_batch(
    Decoder* d,
    const char* buf, const int64_t* offsets, int32_t n_msgs, int32_t channels,
    int32_t* out_rtype, int32_t* out_token, int64_t* out_ts,
    float* out_values, uint8_t* out_chmask,
    int32_t* out_aux0, int32_t* out_aux1,
    int32_t* out_level, int32_t* out_collisions) {
    DirectSink sink{d};
    return decode_json_impl(n_msgs, channels, out_rtype, out_token,
                            out_ts, out_values, out_chmask, out_aux0, 1,
                            out_aux1, 1, out_level, out_collisions,
                            sink, PackedMsgs{buf, offsets});
}

int32_t swtpu_decode_binary_batch(
    Decoder* d,
    const char* buf, const int64_t* offsets, int32_t n_msgs, int32_t channels,
    int32_t* out_rtype, int32_t* out_token, int64_t* out_ts,
    float* out_values, uint8_t* out_chmask,
    int32_t* out_aux0, int32_t* out_aux1,
    int32_t* out_level, int32_t* out_collisions) {
    DirectSink sink{d};
    return decode_binary_impl(n_msgs, channels, out_rtype, out_token,
                              out_ts, out_values, out_chmask, out_aux0, 1,
                              out_aux1, 1, out_level, out_collisions,
                              sink, PackedMsgs{buf, offsets});
}

// Arena-fill entry point: identical decode contract, but out_aux0 and
// out_aux1 are STRIDED columns (row i at out_aux[i * stride]) so the
// scanner writes straight into the aux[:, 0] / aux[:, 1] lanes of a
// preallocated SoA staging arena — the engine's zero-copy batch ingest
// path points every output at arena column slices and no intermediate
// decode buffer ever exists. ``binary`` selects the flat-binary wire
// decoder over the JSON scanner.
int32_t swtpu_decode_arena_batch(
    Decoder* d,
    const char* buf, const int64_t* offsets, int32_t n_msgs, int32_t channels,
    int32_t* out_rtype, int32_t* out_token, int64_t* out_ts,
    float* out_values, uint8_t* out_chmask,
    int32_t* out_aux0, int64_t aux0_stride,
    int32_t* out_aux1, int64_t aux1_stride,
    int32_t* out_level, int32_t* out_collisions, int32_t binary) {
    DirectSink sink{d};
    return binary
               ? decode_binary_impl(n_msgs, channels, out_rtype,
                                    out_token, out_ts, out_values,
                                    out_chmask, out_aux0, aux0_stride,
                                    out_aux1, aux1_stride,
                                    out_level, out_collisions,
                                    sink, PackedMsgs{buf, offsets})
               : decode_json_impl(n_msgs, channels, out_rtype, out_token,
                                  out_ts, out_values, out_chmask, out_aux0,
                                  aux0_stride, out_aux1, aux1_stride,
                                  out_level, out_collisions,
                                  sink, PackedMsgs{buf, offsets});
}

// ------------------------------------------------------------ shard ABI
// Per-shard decode context for the multi-worker arena path: overlay
// interners for first-seen strings + patch records of their uses. One
// ShardCtx belongs to one worker slot; the engine serializes
// reset -> decode -> (new_*/patch_* queries + merge) per batch.

ShardCtx* swtpu_shard_create(Decoder* d) {
    auto* c = new ShardCtx();
    c->d = d;
    c->deferred_row = -1;
    for (int k = 0; k < 4; k++) c->ov[k] = swtpu_interner_create(1 << 22);
    return c;
}

void swtpu_shard_destroy(ShardCtx* c) {
    for (int k = 0; k < 4; k++) swtpu_interner_destroy(c->ov[k]);
    delete c;
}

void swtpu_shard_reset(ShardCtx* c) {
    for (int k = 0; k < 4; k++) {
        if (swtpu_interner_size(c->ov[k]) > 0)
            swtpu_interner_truncate(c->ov[k], 0);
        c->patch[k].clear();
    }
    c->deferred_row = -1;
}

// Ranged arena decode through a shard context: same contract as
// swtpu_decode_arena_batch but interning goes through the shard overlay
// (shared interners are READ-ONLY). Patch rows are shard-relative.
int32_t swtpu_shard_decode_arena_batch(
    ShardCtx* c,
    const char* buf, const int64_t* offsets, int32_t n_msgs, int32_t channels,
    int32_t* out_rtype, int32_t* out_token, int64_t* out_ts,
    float* out_values, uint8_t* out_chmask,
    int32_t* out_aux0, int64_t aux0_stride,
    int32_t* out_aux1, int64_t aux1_stride,
    int32_t* out_level, int32_t* out_collisions, int32_t binary) {
    swtpu_shard_reset(c);
    ShardSink sink{c};
    return binary
               ? decode_binary_impl(n_msgs, channels, out_rtype,
                                    out_token, out_ts, out_values,
                                    out_chmask, out_aux0, aux0_stride,
                                    out_aux1, aux1_stride,
                                    out_level, out_collisions,
                                    sink, PackedMsgs{buf, offsets})
               : decode_json_impl(n_msgs, channels, out_rtype, out_token,
                                  out_ts, out_values, out_chmask, out_aux0,
                                  aux0_stride, out_aux1, aux1_stride,
                                  out_level, out_collisions,
                                  sink, PackedMsgs{buf, offsets});
}

int32_t swtpu_shard_new_count(ShardCtx* c, int32_t kind) {
    return swtpu_interner_size(c->ov[kind]);
}

int32_t swtpu_shard_new_string(ShardCtx* c, int32_t kind, int32_t idx,
                               char* out, int32_t cap) {
    return swtpu_interner_get(c->ov[kind], idx, out, cap);
}

int32_t swtpu_shard_patch_count(ShardCtx* c, int32_t kind) {
    return (int32_t)c->patch[kind].size();
}

void swtpu_shard_patch_fetch(ShardCtx* c, int32_t kind,
                             int32_t* rows, int32_t* idxs, float* vals) {
    const auto& v = c->patch[kind];
    for (size_t i = 0; i < v.size(); i++) {
        rows[i] = v[i].row;
        idxs[i] = v[i].idx;
        vals[i] = v[i].val;
    }
}

}  // extern "C"
