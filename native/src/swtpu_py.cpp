// swtpu_py: CPython-aware entry points over the swtpu batch decoders.
//
// The packed-buffer ABI makes Python pay per batch for b"".join (a 2MB
// memcpy), a 16k-element length scan, and an offsets cumsum before the
// scanner even starts — measured ~1ms of a ~10ms 16k-event batch on the
// 1-core driver host (SURVEY §3.2 hot loop #1's feeder). These entry
// points take the payload LIST itself: pointer+length extraction is one
// C loop over PyBytes objects, the GIL drops for the scan (payload
// buffers stay pinned by the caller's list reference), and no packed
// copy is ever built.
//
// Built as a SEPARATE shared library (libswtpu_py.so) including
// swtpu.cpp, so environments where Python symbols cannot resolve at
// dlopen still load the dependency-free libswtpu.so unchanged.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include "swtpu.cpp"

namespace {

struct SpanMsgs {
    const char* const* ptrs;
    const int64_t* lens;
    std::pair<const char*, const char*> operator()(int32_t i) const {
        return {ptrs[i], ptrs[i] + lens[i]};
    }
};

// thread-local scratch: pointer/length extraction output lives across
// the GIL-released scan; sized once per thread, reused every batch
thread_local std::vector<const char*> t_ptrs;
thread_local std::vector<int64_t> t_lens;
thread_local std::vector<PyObject*> t_objs;

}  // namespace

extern "C" {

// Decode a Python list[bytes] of n_msgs payloads. MUST be called with
// the GIL held (load via ctypes.PyDLL); the GIL is released for the
// scan itself. Returns the decoded count, or -1 when the object is not
// a list of bytes (the caller falls back to the packed path).
int32_t swtpu_decode_pylist(
    Decoder* d, void* pylist, int32_t n_msgs, int32_t channels,
    int32_t* out_rtype, int32_t* out_token, int64_t* out_ts,
    float* out_values, uint8_t* out_chmask,
    int32_t* out_aux0, int32_t* out_aux1,
    int32_t* out_level, int32_t* out_collisions,
    int32_t binary) {
    PyObject* list = (PyObject*)pylist;
    if (!PyList_CheckExact(list) || PyList_GET_SIZE(list) < n_msgs)
        return -1;
    t_ptrs.resize(n_msgs);
    t_lens.resize(n_msgs);
    t_objs.resize(n_msgs);
    for (int32_t i = 0; i < n_msgs; i++) {
        PyObject* o = PyList_GET_ITEM(list, i);
        if (!PyBytes_CheckExact(o)) {
            for (int32_t j = 0; j < i; j++) Py_DECREF(t_objs[j]);
            return -1;
        }
        // STRONG refs across the GIL-released scan: the list reference
        // pins the list, not its items — a caller thread mutating the
        // list mid-scan must not free a buffer under the scanner
        Py_INCREF(o);
        t_objs[i] = o;
        t_ptrs[i] = PyBytes_AS_STRING(o);
        t_lens[i] = (int64_t)PyBytes_GET_SIZE(o);
    }
    SpanMsgs get{t_ptrs.data(), t_lens.data()};
    DirectSink sink{d};
    int32_t ok;
    Py_BEGIN_ALLOW_THREADS
    ok = binary
             ? decode_binary_impl(n_msgs, channels, out_rtype, out_token,
                                  out_ts, out_values, out_chmask, out_aux0,
                                  1, out_aux1, 1, out_level, out_collisions,
                                  sink, get)
             : decode_json_impl(n_msgs, channels, out_rtype, out_token,
                                out_ts, out_values, out_chmask, out_aux0,
                                1, out_aux1, 1, out_level, out_collisions,
                                sink, get);
    Py_END_ALLOW_THREADS
    for (int32_t i = 0; i < n_msgs; i++) Py_DECREF(t_objs[i]);
    return ok;
}

// Arena-fill variant of swtpu_decode_pylist: out_aux0/out_aux1 are
// strided columns (row i at out_aux[i * stride]) aimed at the aux lanes
// of a SoA staging arena; every other output points at arena column
// slices. Same GIL contract as swtpu_decode_pylist.
int32_t swtpu_decode_arena_pylist(
    Decoder* d, void* pylist, int32_t n_msgs, int32_t channels,
    int32_t* out_rtype, int32_t* out_token, int64_t* out_ts,
    float* out_values, uint8_t* out_chmask,
    int32_t* out_aux0, int64_t aux0_stride,
    int32_t* out_aux1, int64_t aux1_stride,
    int32_t* out_level, int32_t* out_collisions,
    int32_t binary) {
    PyObject* list = (PyObject*)pylist;
    if (!PyList_CheckExact(list) || PyList_GET_SIZE(list) < n_msgs)
        return -1;
    t_ptrs.resize(n_msgs);
    t_lens.resize(n_msgs);
    t_objs.resize(n_msgs);
    for (int32_t i = 0; i < n_msgs; i++) {
        PyObject* o = PyList_GET_ITEM(list, i);
        if (!PyBytes_CheckExact(o)) {
            for (int32_t j = 0; j < i; j++) Py_DECREF(t_objs[j]);
            return -1;
        }
        Py_INCREF(o);
        t_objs[i] = o;
        t_ptrs[i] = PyBytes_AS_STRING(o);
        t_lens[i] = (int64_t)PyBytes_GET_SIZE(o);
    }
    SpanMsgs get{t_ptrs.data(), t_lens.data()};
    DirectSink sink{d};
    int32_t ok;
    Py_BEGIN_ALLOW_THREADS
    ok = binary
             ? decode_binary_impl(n_msgs, channels, out_rtype, out_token,
                                  out_ts, out_values, out_chmask, out_aux0,
                                  aux0_stride, out_aux1, aux1_stride,
                                  out_level, out_collisions, sink, get)
             : decode_json_impl(n_msgs, channels, out_rtype, out_token,
                                out_ts, out_values, out_chmask, out_aux0,
                                aux0_stride, out_aux1, aux1_stride,
                                out_level, out_collisions, sink, get);
    Py_END_ALLOW_THREADS
    for (int32_t i = 0; i < n_msgs; i++) Py_DECREF(t_objs[i]);
    return ok;
}

// Sharded (ranged) arena decode over a list[bytes] SLICE: payloads
// [start, start + n_msgs) decode through the shard context's overlay
// interners into output pointers already aimed at the shard's disjoint
// arena row range. Called concurrently from N Python threads — each
// extracts its slice under the GIL, then scans with the GIL released,
// so the scans genuinely parallelize across cores. The shared decoder
// interners are read-only for the whole sharded call (engine lock).
int32_t swtpu_shard_decode_arena_pylist(
    ShardCtx* c, void* pylist, int32_t start, int32_t n_msgs,
    int32_t channels,
    int32_t* out_rtype, int32_t* out_token, int64_t* out_ts,
    float* out_values, uint8_t* out_chmask,
    int32_t* out_aux0, int64_t aux0_stride,
    int32_t* out_aux1, int64_t aux1_stride,
    int32_t* out_level, int32_t* out_collisions,
    int32_t binary) {
    PyObject* list = (PyObject*)pylist;
    if (!PyList_CheckExact(list)
        || PyList_GET_SIZE(list) < (Py_ssize_t)start + n_msgs)
        return -1;
    t_ptrs.resize(n_msgs);
    t_lens.resize(n_msgs);
    t_objs.resize(n_msgs);
    for (int32_t i = 0; i < n_msgs; i++) {
        PyObject* o = PyList_GET_ITEM(list, start + i);
        if (!PyBytes_CheckExact(o)) {
            for (int32_t j = 0; j < i; j++) Py_DECREF(t_objs[j]);
            return -1;
        }
        Py_INCREF(o);
        t_objs[i] = o;
        t_ptrs[i] = PyBytes_AS_STRING(o);
        t_lens[i] = (int64_t)PyBytes_GET_SIZE(o);
    }
    SpanMsgs get{t_ptrs.data(), t_lens.data()};
    int32_t ok;
    Py_BEGIN_ALLOW_THREADS
    swtpu_shard_reset(c);
    ShardSink sink{c};
    ok = binary
             ? decode_binary_impl(n_msgs, channels, out_rtype, out_token,
                                  out_ts, out_values, out_chmask, out_aux0,
                                  aux0_stride, out_aux1, aux1_stride,
                                  out_level, out_collisions, sink, get)
             : decode_json_impl(n_msgs, channels, out_rtype, out_token,
                                out_ts, out_values, out_chmask, out_aux0,
                                aux0_stride, out_aux1, aux1_stride,
                                out_level, out_collisions, sink, get);
    Py_END_ALLOW_THREADS
    for (int32_t i = 0; i < n_msgs; i++) Py_DECREF(t_objs[i]);
    return ok;
}

// Owning-rank partition of a list[bytes] batch without decoding (the
// cluster facade's token-hash router; same GIL contract as
// swtpu_decode_pylist). Returns 0, or -1 when the object is not a list
// of bytes (caller falls back to the Python partitioner).
int32_t swtpu_route_pylist(
    void* pylist, int32_t n_msgs, int32_t n_ranks,
    int32_t* out_rank, int32_t binary) {
    PyObject* list = (PyObject*)pylist;
    if (!PyList_CheckExact(list) || PyList_GET_SIZE(list) < n_msgs)
        return -1;
    t_ptrs.resize(n_msgs);
    t_lens.resize(n_msgs);
    t_objs.resize(n_msgs);
    for (int32_t i = 0; i < n_msgs; i++) {
        PyObject* o = PyList_GET_ITEM(list, i);
        if (!PyBytes_CheckExact(o)) {
            for (int32_t j = 0; j < i; j++) Py_DECREF(t_objs[j]);
            return -1;
        }
        Py_INCREF(o);
        t_objs[i] = o;
        t_ptrs[i] = PyBytes_AS_STRING(o);
        t_lens[i] = (int64_t)PyBytes_GET_SIZE(o);
    }
    SpanMsgs get{t_ptrs.data(), t_lens.data()};
    Py_BEGIN_ALLOW_THREADS
    if (binary)
        route_binary_impl(n_msgs, n_ranks, out_rank, get);
    else
        route_json_impl(n_msgs, n_ranks, out_rank, get);
    Py_END_ALLOW_THREADS
    for (int32_t i = 0; i < n_msgs; i++) Py_DECREF(t_objs[i]);
    return 0;
}

}  // extern "C"
